"""Parallelism tests on the virtual 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8 — SURVEY §4's no-hardware strategy)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import ops, parallel
from kata_xpu_device_plugin_tpu.models import llama3_train_test, tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import forward, init_params
from kata_xpu_device_plugin_tpu.ops.attention import reference_attention


def test_virtual_mesh_available():
    assert jax.device_count() == 8


def test_build_mesh_shapes():
    mesh = parallel.build_mesh()
    assert mesh.devices.size == 8
    assert set(mesh.axis_names) == {"data", "fsdp", "model"}
    assert parallel.default_mesh_shape(8)["model"] == 4


def test_collectives_pmap_all_reduce():
    n = jax.device_count()
    out = ops.pmap_all_reduce(jnp.ones((n, 1), jnp.float32))
    assert out.shape == (n, 1)
    np.testing.assert_allclose(out, n)


def test_ring_all_reduce_matches_psum():
    mesh = parallel.seq_mesh(8)
    x = jnp.arange(16, dtype=jnp.float32)
    expected = np.arange(16, dtype=np.float32).reshape(8, 2).sum(0)  # [56, 64]
    psum = ops.mesh_all_reduce(mesh, x, "seq")
    np.testing.assert_allclose(psum, expected)
    # ring keeps the sharded layout: every 2-element shard holds the total
    ring = np.asarray(ops.ring_all_reduce(mesh, x, "seq")).reshape(8, 2)
    np.testing.assert_allclose(ring, np.broadcast_to(expected, (8, 2)))


def test_all_gather_reduce_scatter():
    mesh = parallel.seq_mesh(8)
    x = jnp.arange(8, dtype=jnp.float32)
    gathered = ops.all_gather(mesh, x, "seq")
    np.testing.assert_allclose(gathered, x)
    rs = ops.reduce_scatter(mesh, jnp.ones((8,), jnp.float32), "seq")
    np.testing.assert_allclose(rs, 8.0)


def test_ring_attention_matches_reference():
    mesh = parallel.seq_mesh(8)
    B, S, H, KV, D = 2, 64, 4, 2, 16
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    ring_attn = parallel.make_ring_attention(mesh)
    out_ring = ring_attn(q, k, v)
    out_ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-5)


def test_sharded_params_and_forward_match_single_device():
    # fp32 compute: GSPMD must be bit-compatible up to reduction reordering
    # (~1e-5); bf16 reorders diverge visibly and are not a correctness signal.
    from dataclasses import replace

    cfg = replace(tiny_test_config(), dtype=jnp.float32)
    mesh = parallel.build_mesh()
    key = jax.random.PRNGKey(0)
    params_single = init_params(key, cfg)
    params_sharded = parallel.init_sharded_params(key, cfg, mesh)
    # identical values, different placement
    np.testing.assert_allclose(
        np.asarray(params_single["layers"]["wq"]),
        np.asarray(jax.device_get(params_sharded["layers"]["wq"])),
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    logits_single = forward(params_single, tokens, cfg)
    tokens_sharded = parallel.shard_batch(tokens, mesh)
    logits_sharded = jax.jit(lambda p, t: forward(p, t, cfg))(
        params_sharded, tokens_sharded
    )
    np.testing.assert_allclose(
        np.asarray(logits_single), np.asarray(jax.device_get(logits_sharded)),
        rtol=1e-4, atol=1e-4,
    )


@pytest.mark.slow
def test_sharded_train_step_runs_and_learns():
    cfg = llama3_train_test()
    mesh = parallel.build_mesh()
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    # params + opt state actually sharded (not replicated everywhere)
    wq_shard = state["params"]["layers"]["wq"].sharding
    assert wq_shard.spec == parallel.PARAM_RULES["layers.wq"]
    tokens = parallel.shard_batch(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size), mesh
    )
    losses = []
    for _ in range(4):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert int(state["step"]) == 4
    assert losses[-1] < losses[0], losses


def test_seq_composed_train_step_matches_unsharded():
    """Sequence parallelism composed with fsdp and tp on ONE mesh
    (seq2×fsdp2×model2): ring attention rides the mesh's seq axis inside
    the GSPMD train step, and the first-step loss must match the plain
    unsharded loss on the same params/tokens (VERDICT r3 missing #2)."""
    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        next_token_loss,
    )

    cfg = llama3_train_test()
    mesh = parallel.build_mesh({"data": 1, "fsdp": 2, "model": 2, "seq": 2})
    assert "seq" in mesh.axis_names
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    state, loss = step(state, parallel.shard_batch(toks, mesh))

    ref_params = init_params(jax.random.PRNGKey(0), cfg)
    ref_loss = next_token_loss(ref_params, toks, cfg)
    # fp32 ring attention accumulates blockwise (online softmax + per-step
    # merges), so the loss scalar differs from the reference at a few e-4
    # relative; 1e-3 still catches any wiring bug by orders of magnitude.
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-3)

    # And it trains: a few more steps reduce the loss.
    losses = [float(loss)]
    for _ in range(3):
        state, loss = step(state, parallel.shard_batch(toks, mesh))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_optimizer_schedule_and_clipping():
    """make_optimizer's warmup-cosine schedule and global-norm clipping
    through the sharded train step: warmup step 1 must move params LESS
    than the constant-lr step (lr ramps from 0), clipping must bound the
    update, and the chained optimizer's state still shards (fsdp rules
    apply through optax.chain's tuple state)."""
    cfg = llama3_train_test()
    mesh = parallel.build_mesh({"data": 1, "fsdp": 2, "model": 2},
                               devices=jax.devices()[:4])
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                              cfg.vocab_size)

    def delta_after_one_step(opt):
        init_state, step = parallel.make_train_step(cfg, mesh, optimizer=opt)
        state = init_state(jax.random.PRNGKey(0))
        w0 = np.asarray(jax.device_get(state["params"]["layers"]["wq"]))
        state, loss = step(state, parallel.shard_batch(toks, mesh))
        w1 = np.asarray(jax.device_get(state["params"]["layers"]["wq"]))
        return float(np.abs(w1 - w0).sum()), state

    base, state = delta_after_one_step(parallel.make_optimizer(lr=3e-4))
    warm, _ = delta_after_one_step(
        parallel.make_optimizer(lr=3e-4, warmup_steps=100, total_steps=1000)
    )
    clip, _ = delta_after_one_step(
        parallel.make_optimizer(lr=3e-4, grad_clip=1e-4)
    )
    assert warm < base * 0.1, (warm, base)   # lr ≈ lr/100 at step 1
    assert clip < base, (clip, base)         # tiny clip bounds the update
    # Chained optimizer state still carries the fsdp shardings.
    mu_wq = jax.tree.leaves(
        jax.tree.map(lambda x: x, state["opt"],
                     is_leaf=lambda x: hasattr(x, "sharding"))
    )
    assert any(
        getattr(leaf, "sharding", None) is not None
        and leaf.sharding.spec == parallel.PARAM_RULES["layers.wq"]
        and leaf.shape == state["params"]["layers"]["wq"].shape
        for leaf in jax.tree.leaves(state["opt"])
        if hasattr(leaf, "sharding")
    )


def test_gradient_accumulation_matches_full_batch():
    """accum_steps=2 over [8, S] must produce the same loss and updated
    params as the full-batch step on identical tokens (dense config:
    mean of equal-sized microbatch means == full-batch mean), while only
    one microbatch of activations is ever live."""
    cfg = llama3_train_test()
    mesh = parallel.build_mesh({"data": 1, "fsdp": 2, "model": 2},
                               devices=jax.devices()[:4])
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                              cfg.vocab_size)

    def run(accum):
        init_state, step = parallel.make_train_step(cfg, mesh,
                                                    accum_steps=accum)
        state = init_state(jax.random.PRNGKey(0))
        state, loss = step(state, parallel.shard_batch(toks, mesh))
        fp = sum(
            float(jnp.sum(jnp.abs(x.astype(jnp.float32))))
            for x in jax.tree.leaves(state["params"])
        )
        return float(loss), fp

    loss1, fp1 = run(1)
    loss2, fp2 = run(2)
    np.testing.assert_allclose(loss2, loss1, rtol=1e-5)
    np.testing.assert_allclose(fp2, fp1, rtol=1e-5)

    with pytest.raises(ValueError, match="accum_steps"):
        parallel.make_train_step(cfg, mesh, accum_steps=0)


# ----- pipeline parallelism (pp) -------------------------------------------


def _mlp_stage(params, x):
    return jnp.tanh(x @ params["w"]) + x


def _make_stages(n_stages, dim, key):
    keys = jax.random.split(key, n_stages)
    return [{"w": jax.random.normal(k, (dim, dim)) * 0.1} for k in keys]


def test_pipeline_matches_sequential():
    n_stages, dim, n_mb, mb = 4, 8, 6, 2
    mesh = parallel.pipe_mesh(n_stages)
    stages = _make_stages(n_stages, dim, jax.random.PRNGKey(0))
    stacked = parallel.stack_stage_params(stages)
    mbs = jax.random.normal(jax.random.PRNGKey(1), (n_mb, mb, dim))
    pipelined = parallel.make_pipeline(_mlp_stage, n_stages, mesh)
    out = jax.jit(pipelined)(stacked, mbs)
    ref = parallel.sequential_reference(_mlp_stage, stages, mbs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pipeline_single_microbatch_and_full_width():
    n_stages = 8  # every device a stage
    mesh = parallel.pipe_mesh(n_stages)
    stages = _make_stages(n_stages, 4, jax.random.PRNGKey(2))
    stacked = parallel.stack_stage_params(stages)
    mbs = jax.random.normal(jax.random.PRNGKey(3), (1, 2, 4))
    out = jax.jit(parallel.make_pipeline(_mlp_stage, n_stages, mesh))(stacked, mbs)
    ref = parallel.sequential_reference(_mlp_stage, stages, mbs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pipeline_transformer_block_stages():
    """Pipeline real decoder layers: each stage is one transformer block."""
    from kata_xpu_device_plugin_tpu.models.transformer import _layer
    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention

    cfg = tiny_test_config()
    n_stages, n_mb, mb, seq = 2, 2, 2, 8
    mesh = parallel.pipe_mesh(n_stages)
    params = init_params(jax.random.PRNGKey(0), cfg)
    positions = jnp.arange(seq)[None, :]

    def stage(layer_params, x):
        y, _cache, _aux = _layer(
            cfg, reference_attention, x, layer_params, positions
        )
        return y

    # init_params stacks layers on axis 0 already; take the first n_stages.
    stacked = jax.tree.map(lambda p: p[:n_stages], params["layers"])
    stage_list = [jax.tree.map(lambda p, i=i: p[i], stacked) for i in range(n_stages)]
    x = jax.random.normal(jax.random.PRNGKey(1), (n_mb, mb, seq, cfg.d_model))
    out = jax.jit(parallel.make_pipeline(stage, n_stages, mesh))(stacked, x)
    ref = parallel.sequential_reference(stage, stage_list, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_transformer_pipeline_matches_unpipelined_forward():
    """VERDICT r1 item 5: the FULL decoder (embed → staged layer chunks over
    the pipe axis → final norm/unembed) equals the unpipelined forward."""
    cfg = tiny_test_config(n_layers=4, dtype=jnp.float32)
    n_stages, n_mb, mb, seq = 4, 3, 2, 8
    mesh = parallel.pipe_mesh(n_stages)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (n_mb, mb, seq), 0, cfg.vocab_size, dtype=jnp.int32
    )
    pipelined = parallel.make_transformer_pipeline(cfg, n_stages, mesh)
    out = jax.jit(pipelined)(params, tokens)
    ref = np.stack(
        [np.asarray(forward(params, tokens[m], cfg)) for m in range(n_mb)]
    )
    assert out.shape == (n_mb, mb, seq, cfg.vocab_size)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_transformer_pipeline_multilayer_stages():
    """8 layers over 2 stages: each stage scans a 4-layer chunk."""
    cfg = tiny_test_config(n_layers=8, dtype=jnp.float32)
    mesh = parallel.pipe_mesh(2)
    params = init_params(jax.random.PRNGKey(2), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (2, 2, 8), 0, cfg.vocab_size, dtype=jnp.int32
    )
    out = jax.jit(parallel.make_transformer_pipeline(cfg, 2, mesh))(params, tokens)
    ref = np.stack([np.asarray(forward(params, tokens[m], cfg)) for m in range(2)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_transformer_pipeline_rejects_indivisible_layers():
    cfg = tiny_test_config(n_layers=3)
    mesh = parallel.pipe_mesh(2)
    with pytest.raises(ValueError, match="not divisible"):
        parallel.make_transformer_pipeline(cfg, 2, mesh)


# ----- expert parallelism (ep) ---------------------------------------------


def test_moe_matches_per_token_reference():
    cfg = ops.MoEConfig(d_model=8, d_ff=16, num_experts=4, capacity_factor=4.0)
    params = ops.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    y, aux = ops.moe_ffn(params, x, cfg)
    ref = ops.reference_moe(params, x, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4, atol=1e-5)
    assert float(aux) >= 1.0 - 1e-5  # Switch aux loss lower bound at uniform


def test_moe_capacity_drops_tokens_to_zero():
    cfg = ops.MoEConfig(d_model=4, d_ff=8, num_experts=2, capacity_factor=0.01)
    params = ops.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, cfg.d_model))
    y, _ = ops.moe_ffn(params, x, cfg)
    # capacity=1 per expert: at most num_experts tokens produce output
    nonzero_tokens = int(jnp.sum(jnp.any(y.reshape(-1, cfg.d_model) != 0, axis=-1)))
    assert nonzero_tokens <= cfg.num_experts


def test_moe_top2_matches_per_token_reference():
    """VERDICT r1 item 6: top-k routing with the sort-based dispatch must
    match the per-token weighted-sum reference when capacity is ample."""
    cfg = ops.MoEConfig(
        d_model=8, d_ff=16, num_experts=4, capacity_factor=4.0, top_k=2
    )
    params = ops.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    y, aux = jax.jit(lambda p, t: ops.moe_ffn(p, t, cfg))(params, x)
    ref = ops.reference_moe(params, x, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4, atol=1e-5)
    # The Switch ≥1 lower bound is top-1-specific (top-k flattens the routed
    # fractions below the softmax mass); the loss just has to be finite and
    # positive here.
    assert 0.0 < float(aux) < float(cfg.num_experts)


def test_moe_top2_expert_parallel_matches_unsharded():
    n = jax.device_count()
    cfg = ops.MoEConfig(
        d_model=8, d_ff=16, num_experts=n, capacity_factor=4.0, top_k=2
    )
    params = ops.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    y_local, _ = ops.moe_ffn(params, x, cfg)
    mesh = ops.expert_mesh(n)
    from jax.sharding import NamedSharding

    specs = ops.moe_param_specs()
    params_sharded = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in params.items()
    }
    y_ep, _ = jax.jit(lambda p, t: ops.moe_ffn(p, t, cfg, mesh=mesh))(params_sharded, x)
    np.testing.assert_allclose(
        np.asarray(y_local), np.asarray(jax.device_get(y_ep)), rtol=1e-4, atol=1e-5
    )


def test_moe_gates_sum_to_one_for_topk():
    """k>1 gates renormalize over the chosen experts (Mixtral semantics)."""
    from kata_xpu_device_plugin_tpu.ops.moe import _route

    cfg = ops.MoEConfig(d_model=8, d_ff=16, num_experts=4, top_k=3)
    params = ops.init_moe_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.normal(jax.random.PRNGKey(1), (16, cfg.d_model))
    gates, top_e, _ = _route(params, toks, cfg)
    np.testing.assert_allclose(np.asarray(gates.sum(-1)), 1.0, rtol=1e-5)
    # expert ids distinct per token
    assert all(len(set(row)) == cfg.top_k for row in np.asarray(top_e))


def test_moe_expert_parallel_matches_unsharded():
    """EP via GSPMD: sharded-expert execution must be numerically identical
    and actually shard the expert tensors across the mesh."""
    n = jax.device_count()
    cfg = ops.MoEConfig(d_model=8, d_ff=16, num_experts=n, capacity_factor=4.0)
    params = ops.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    y_local, _ = ops.moe_ffn(params, x, cfg)

    mesh = ops.expert_mesh(n)
    from jax.sharding import NamedSharding

    specs = ops.moe_param_specs()
    params_sharded = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in params.items()
    }
    assert not params_sharded["w_in"].sharding.is_fully_replicated
    y_ep, _ = jax.jit(lambda p, t: ops.moe_ffn(p, t, cfg, mesh=mesh))(params_sharded, x)
    np.testing.assert_allclose(
        np.asarray(y_local), np.asarray(jax.device_get(y_ep)), rtol=1e-4, atol=1e-5
    )


def test_ring_attention_flash_fused():
    """Ring attention with the pallas block kernel per ring step (VERDICT r2
    item 6): global-causal numerics must still match reference_attention."""
    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import seq_mesh
    from kata_xpu_device_plugin_tpu.parallel.ring import make_ring_attention

    B, S, H, KV, D = 1, 4 * 128, 2, 1, 64  # S_loc=128: block-kernel eligible
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    mesh = seq_mesh(4)
    ref = reference_attention(q, k, v, causal=True)
    ring = make_ring_attention(mesh, use_flash=True, flash_interpret=True)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-4)


def test_ring_attention_softcap_matches_reference():
    """Gemma-2's logit softcap through sequence-parallel ring attention —
    the einsum path AND the per-step flash block kernel must match the
    capped reference, so softcap configs train sp."""
    from functools import partial

    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import seq_mesh
    from kata_xpu_device_plugin_tpu.parallel.ring import make_ring_attention

    cap = 4.0
    B, S, H, KV, D = 1, 4 * 128, 2, 1, 64
    keys = jax.random.split(jax.random.PRNGKey(17), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    mesh = seq_mesh(4)
    ref = reference_attention(q, k, v, causal=True, logits_softcap=cap)
    for flash in (False, True):
        ring = make_ring_attention(mesh, use_flash=flash, flash_interpret=flash)
        out = jax.jit(partial(ring, logits_softcap=cap))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-4,
            err_msg=f"flash={flash}",
        )


def test_ring_attention_flash_fused_gradients():
    """The fused sp path must TRAIN: gradients through the per-block pallas
    kernel (lse cotangent folded into the recompute) match the reference."""
    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import seq_mesh
    from kata_xpu_device_plugin_tpu.parallel.ring import make_ring_attention

    B, S, H, KV, D = 1, 4 * 128, 2, 1, 64
    keys = jax.random.split(jax.random.PRNGKey(8), 4)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    dout = jax.random.normal(keys[3], q.shape, jnp.float32)
    ring = make_ring_attention(seq_mesh(4), use_flash=True, flash_interpret=True)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(ring(q, k, v) * dout), argnums=(0, 1, 2)
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v, causal=True) * dout),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, nm in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4, err_msg=f"d{nm}"
        )


@pytest.mark.parametrize("window", [5, 12, 30, 64])
def test_ring_attention_sliding_window_matches_reference(window):
    """Sliding-window configs through the ring (VERDICT r4 weak #2): the
    global band mask must match reference_attention for windows smaller
    than a shard, spanning shards, and the full sequence — on the einsum
    path. The hop count is bounded (the windowed ring is CHEAPER), which
    the masked numerics implicitly verify: a dropped-but-needed block
    would be a large error."""
    from functools import partial

    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import seq_mesh
    from kata_xpu_device_plugin_tpu.parallel.ring import make_ring_attention

    B, S, H, KV, D = 2, 64, 4, 2, 16  # S_loc = 8 on the 8-way mesh
    keys = jax.random.split(jax.random.PRNGKey(21), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    ring = make_ring_attention(seq_mesh(8))
    out = jax.jit(partial(ring, window=window))(q, k, v)
    ref = reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_sliding_window_flash_and_gradients():
    """Windowed ring on the per-step pallas block kernel: forward AND
    gradients must match the windowed reference (the band mask lives in
    the kernel's fwd and both bwd passes; the ring merge handles blocks
    whose rows are fully out of band via their −inf logsumexp)."""
    from functools import partial

    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import seq_mesh
    from kata_xpu_device_plugin_tpu.parallel.ring import make_ring_attention

    window = 160  # spans a 128-wide shard boundary: 2 live hops of 3
    B, S, H, KV, D = 1, 4 * 128, 2, 1, 64
    keys = jax.random.split(jax.random.PRNGKey(23), 4)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    dout = jax.random.normal(keys[3], q.shape, jnp.float32)
    ring = make_ring_attention(seq_mesh(4), use_flash=True, flash_interpret=True)

    out = jax.jit(partial(ring, window=window))(q, k, v)
    ref = reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-4)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(ring(q, k, v, window=window) * dout),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(
            reference_attention(q, k, v, causal=True, window=window) * dout
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, nm in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4, err_msg=f"d{nm}"
        )


def test_sharded_flash_attention_matches_reference():
    """The shard_map flash wrapper (VERDICT r4 weak #3): the pallas kernel
    partitions over batch (data×fsdp) and head (model) axes of a dense
    mesh — forward and gradients must match the reference, including the
    windowed and softcapped variants."""
    from functools import partial

    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import make_sharded_attention

    mesh = parallel.build_mesh({"data": 2, "fsdp": 2, "model": 2})
    B, S, H, KV, D = 4, 128, 4, 2, 64
    keys = jax.random.split(jax.random.PRNGKey(31), 4)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    dout = jax.random.normal(keys[3], q.shape, jnp.float32)
    attn = make_sharded_attention(
        mesh, head_axis="model", kv_head_axis="model",
        use_flash=True, flash_interpret=True,
    )

    for kw in ({}, {"window": 40}, {"logits_softcap": 4.0}):
        out = jax.jit(partial(attn, **kw))(q, k, v)
        ref_kw = dict(kw)
        ref = reference_attention(q, k, v, causal=True, **ref_kw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-4, err_msg=str(kw))

    gf = jax.grad(lambda q, k, v: jnp.sum(attn(q, k, v) * dout),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v, causal=True) * dout),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, nm in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4, err_msg=f"d{nm}"
        )


def test_train_step_with_sharded_flash_matches_reference_step():
    """The full GSPMD train step with the shard_map-wrapped flash kernel as
    its attention (the default on TPU): first-step loss matches the plain
    unsharded reference loss — the kernel partitions instead of
    replicating, and numerics hold through value_and_grad."""
    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        next_token_loss,
    )
    from kata_xpu_device_plugin_tpu.parallel import make_sharded_attention

    cfg = llama3_train_test()
    mesh = parallel.build_mesh({"data": 2, "fsdp": 2, "model": 2})
    attn = make_sharded_attention(
        mesh, head_axis="model", kv_head_axis="model",
        use_flash=True, flash_interpret=True,
    )
    init_state, step = parallel.make_train_step(cfg, mesh, attn_fn=attn)
    state = init_state(jax.random.PRNGKey(0))
    # S=128: a valid flash block (the forced kernel rejects indivisible
    # lengths); the model forwards the FULL sequence for the loss.
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, cfg.vocab_size)
    state, loss = step(state, parallel.shard_batch(toks, mesh))

    ref_loss = next_token_loss(init_params(jax.random.PRNGKey(0), cfg), toks, cfg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-3)


def test_windowed_seq_composed_train_step():
    """A sliding-window config (Mistral-style) through the seq×fsdp×tp
    composed GSPMD train step — the case VERDICT r4 weak #2 said could not
    train sequence-parallel at all. First-step loss must match the plain
    unsharded loss, and the step must train."""
    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        next_token_loss,
    )

    cfg = llama3_train_test(sliding_window=10)
    mesh = parallel.build_mesh({"data": 1, "fsdp": 2, "model": 2, "seq": 2})
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    state, loss = step(state, parallel.shard_batch(toks, mesh))

    ref_loss = next_token_loss(init_params(jax.random.PRNGKey(0), cfg), toks, cfg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-3)

    losses = [float(loss)]
    for _ in range(3):
        state, loss = step(state, parallel.shard_batch(toks, mesh))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_gemma2_window_cycle_seq_composed_train_step():
    """Gemma-2's attn_windows cycle (alternating local/global layers, logit
    softcap) on the seq-composed mesh: each layer's window rides its own
    ring shard_map; loss must match the unsharded reference."""
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        next_token_loss,
    )

    cfg = gemma2_test_config(dtype=jnp.float32)
    assert cfg.attn_windows, "test config must carry a window cycle"
    mesh = parallel.build_mesh({"data": 1, "fsdp": 2, "model": 2, "seq": 2})
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    state, loss = step(state, parallel.shard_batch(toks, mesh))

    ref_loss = next_token_loss(init_params(jax.random.PRNGKey(0), cfg), toks, cfg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-3)


@pytest.mark.parametrize("n,kv", [(4, 8), (4, 2), (4, 1), (8, 2), (2, 4)])
def test_ulysses_attention_matches_reference(n, kv):
    """Ulysses sp (all-to-all head-parallel attention): numerics must match
    full attention for KV%n==0 (all-to-all KV) and n%KV==0 (gather+slice)."""
    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import make_ulysses_attention, seq_mesh

    B, S, H, D = 2, n * 16, 8, 16
    keys = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, kv, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, kv, D), jnp.float32)
    ua = make_ulysses_attention(seq_mesh(n), attn_fn=reference_attention)
    out = jax.jit(ua)(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_ulysses_window_and_softcap_match_reference():
    """r5: Ulysses forwards the sliding-window band and Gemma-2 softcap
    into its full-sequence inner attention — both must match the
    reference (ring gained the same support; sp strategy choice should
    not constrain the model family)."""
    from functools import partial

    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
    from kata_xpu_device_plugin_tpu.parallel import make_ulysses_attention, seq_mesh

    B, S, H, KV, D = 2, 64, 8, 4, 16
    keys = jax.random.split(jax.random.PRNGKey(19), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    ua = make_ulysses_attention(seq_mesh(4), attn_fn=reference_attention)
    for kw in ({"window": 20}, {"logits_softcap": 4.0},
               {"window": 12, "logits_softcap": 4.0}):
        out = jax.jit(partial(ua, **kw))(q, k, v)
        ref = reference_attention(q, k, v, causal=True, **kw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5, err_msg=str(kw))


def test_ulysses_rejects_bad_degrees():
    from kata_xpu_device_plugin_tpu.parallel import make_ulysses_attention, seq_mesh

    mesh = seq_mesh(8)
    q = jnp.zeros((1, 64, 4, 16))  # H=4 not divisible by sp=8
    with pytest.raises(ValueError, match="n_heads"):
        jax.jit(make_ulysses_attention(mesh))(q, q, q)
    q = jnp.zeros((1, 64, 8, 16))
    k = jnp.zeros((1, 64, 3, 16))  # KV=3: neither divides nor is divided by 8
    with pytest.raises(ValueError, match="n_kv_heads"):
        jax.jit(make_ulysses_attention(mesh))(q, k, k)


def test_qkv_bias_train_step_matches_unsharded():
    """Qwen2-style qkv biases through the full GSPMD train step: the bias
    params shard over the model axis alongside their matrices (PARAM_RULES
    layers.bq/bk/bv), gradients flow into them, and the first-step loss
    matches the plain unsharded loss on identical params/tokens."""
    from dataclasses import replace

    from kata_xpu_device_plugin_tpu.models.transformer import next_token_loss

    cfg = replace(llama3_train_test(), qkv_bias=True)
    mesh = parallel.build_mesh({"data": 2, "fsdp": 2, "model": 2})
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(3))
    assert "bq" in state["params"]["layers"]
    toks = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, cfg.vocab_size)
    state, loss = step(state, parallel.shard_batch(toks, mesh))

    ref_loss = next_token_loss(init_params(jax.random.PRNGKey(3), cfg), toks, cfg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-3)
    # the optimizer really updated the biases (grads are nonzero)
    assert float(jnp.abs(state["params"]["layers"]["bq"]).max()) > 0.0


def test_gemma3_dual_rope_seq_composed_train_step():
    """Gemma-3's full block — QK-norms, window cycle, DUAL per-layer rope
    (local base freq + linearly rescaled global) — through the seq×fsdp×tp
    composed GSPMD train step. The rope cycles are applied in _layer
    before the ring attention override, so they must survive the seq
    sharding unchanged: first-step loss matches the unsharded reference."""
    from dataclasses import replace as _replace

    from kata_xpu_device_plugin_tpu.models import gemma3_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        next_token_loss,
    )

    cfg = _replace(gemma3_test_config(), dtype=jnp.float32)
    assert cfg.rope_theta_cycle and cfg.qk_norm
    mesh = parallel.build_mesh({"data": 1, "fsdp": 2, "model": 2, "seq": 2})
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(6))
    toks = jax.random.randint(jax.random.PRNGKey(7), (4, 32), 0, cfg.vocab_size)
    state, loss = step(state, parallel.shard_batch(toks, mesh))

    ref_loss = next_token_loss(init_params(jax.random.PRNGKey(6), cfg), toks, cfg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-3)
