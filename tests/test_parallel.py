"""Parallelism tests on the virtual 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8 — SURVEY §4's no-hardware strategy)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import ops, parallel
from kata_xpu_device_plugin_tpu.models import llama3_train_test, tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import forward, init_params
from kata_xpu_device_plugin_tpu.ops.attention import reference_attention


def test_virtual_mesh_available():
    assert jax.device_count() == 8


def test_build_mesh_shapes():
    mesh = parallel.build_mesh()
    assert mesh.devices.size == 8
    assert set(mesh.axis_names) == {"data", "fsdp", "model"}
    assert parallel.default_mesh_shape(8)["model"] == 4


def test_collectives_pmap_all_reduce():
    n = jax.device_count()
    out = ops.pmap_all_reduce(jnp.ones((n, 1), jnp.float32))
    assert out.shape == (n, 1)
    np.testing.assert_allclose(out, n)


def test_ring_all_reduce_matches_psum():
    mesh = parallel.seq_mesh(8)
    x = jnp.arange(16, dtype=jnp.float32)
    expected = np.arange(16, dtype=np.float32).reshape(8, 2).sum(0)  # [56, 64]
    psum = ops.mesh_all_reduce(mesh, x, "seq")
    np.testing.assert_allclose(psum, expected)
    # ring keeps the sharded layout: every 2-element shard holds the total
    ring = np.asarray(ops.ring_all_reduce(mesh, x, "seq")).reshape(8, 2)
    np.testing.assert_allclose(ring, np.broadcast_to(expected, (8, 2)))


def test_all_gather_reduce_scatter():
    mesh = parallel.seq_mesh(8)
    x = jnp.arange(8, dtype=jnp.float32)
    gathered = ops.all_gather(mesh, x, "seq")
    np.testing.assert_allclose(gathered, x)
    rs = ops.reduce_scatter(mesh, jnp.ones((8,), jnp.float32), "seq")
    np.testing.assert_allclose(rs, 8.0)


def test_ring_attention_matches_reference():
    mesh = parallel.seq_mesh(8)
    B, S, H, KV, D = 2, 64, 4, 2, 16
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(keys[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(keys[2], (B, S, KV, D), jnp.float32)
    ring_attn = parallel.make_ring_attention(mesh)
    out_ring = ring_attn(q, k, v)
    out_ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-5)


def test_sharded_params_and_forward_match_single_device():
    # fp32 compute: GSPMD must be bit-compatible up to reduction reordering
    # (~1e-5); bf16 reorders diverge visibly and are not a correctness signal.
    from dataclasses import replace

    cfg = replace(tiny_test_config(), dtype=jnp.float32)
    mesh = parallel.build_mesh()
    key = jax.random.PRNGKey(0)
    params_single = init_params(key, cfg)
    params_sharded = parallel.init_sharded_params(key, cfg, mesh)
    # identical values, different placement
    np.testing.assert_allclose(
        np.asarray(params_single["layers"]["wq"]),
        np.asarray(jax.device_get(params_sharded["layers"]["wq"])),
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    logits_single = forward(params_single, tokens, cfg)
    tokens_sharded = parallel.shard_batch(tokens, mesh)
    logits_sharded = jax.jit(lambda p, t: forward(p, t, cfg))(
        params_sharded, tokens_sharded
    )
    np.testing.assert_allclose(
        np.asarray(logits_single), np.asarray(jax.device_get(logits_sharded)),
        rtol=1e-4, atol=1e-4,
    )


@pytest.mark.slow
def test_sharded_train_step_runs_and_learns():
    cfg = llama3_train_test()
    mesh = parallel.build_mesh()
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    # params + opt state actually sharded (not replicated everywhere)
    wq_shard = state["params"]["layers"]["wq"].sharding
    assert wq_shard.spec == parallel.PARAM_RULES["layers.wq"]
    tokens = parallel.shard_batch(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size), mesh
    )
    losses = []
    for _ in range(4):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert int(state["step"]) == 4
    assert losses[-1] < losses[0], losses
