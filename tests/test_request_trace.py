"""Request lifecycle tracing, trace-context propagation, and the crash
flight recorder (ISSUE 11).

Oracle — ATTRIBUTION IS COMPLETE: every submitted request ends with
exactly one ``request_trace`` event whose six phase fields sum to the
request's wall clock (the ledger is a state machine — every moment of a
request's life is in exactly one phase), across the serving matrix
(paged/slotted × overlap × chunked × preemption × recovery). Telemetry
must also be INVISIBLE in the output: greedy tokens are bit-identical
with the sink+recorder armed and disarmed. The daemon→guest half:
``Allocate`` stamps ``KATA_TPU_TRACE_CTX``, the server adopts it, and
every serving event (the PR 10 recovery/degrade/fatal vocabulary
included — the satellite) carries the allocation trace id, which is
what makes a flight-recorder postmortem joinable end to end.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest.resilience import (
    FaultInjector,
    FaultSpec,
)
from kata_xpu_device_plugin_tpu.guest.serving import (
    PHASES,
    GenerationServer,
)
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params
from kata_xpu_device_plugin_tpu.obs import flight


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=2):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


def _serve(params, cfg, prompts, budgets=8, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("chunk", 4)
    kw.setdefault("recovery_backoff_s", 0.0)
    srv = GenerationServer(params, cfg, **kw)
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, m) for p, m in zip(prompts, budgets)]
    res = srv.run()
    return rids, res, srv


def _events(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _run_with_sink(tmp_path, fn):
    sink = obs.EventSink(str(tmp_path / "ev.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        out = fn()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    return out, _events(tmp_path / "ev.jsonl")


# ----- the attribution matrix (tentpole b) ----------------------------------


MATRIX = {
    "slotted_lockstep": dict(overlap=False),
    "slotted_overlap": dict(overlap=True),
    "paged_overlap": dict(kv_pool_tokens=4 * 32, kv_block_size=8),
    "chunked": dict(prefill_buckets=(16,), sched_policy="slo_chunked",
                    prefill_chunk=4, itl_slo_ms=0.0),
    "preemption": dict(max_batch=4, kv_pool_tokens=32 + 3 * 8,
                       kv_block_size=8),
    "recovery": dict(checkpoint_rounds=2),
}


@pytest.mark.parametrize("case", sorted(MATRIX))
def test_phase_attribution_sums_to_wall(model, tmp_path, case):
    """The acceptance invariant: one request_trace per rid, phases sum
    to wall time within 5% (the slack is 6-decimal rounding — the
    ledger is exact by construction), across the serving matrix."""
    cfg, params = model
    kw = dict(MATRIX[case])
    if case == "preemption":
        prompts = _prompts(cfg, [4, 9, 6, 12, 3, 7, 5, 8])
        budgets = 14
    elif case == "chunked":
        # The test_scheduler workload: long mixed prompts + ragged
        # budgets so deferral (slo_ms=0) actually chunks admissions
        # once the bootstrap estimates exist.
        prompts = _prompts(cfg, [14, 9, 12, 7, 15, 11])
        budgets = [6, 12, 9, 5, 11, 7]
    else:
        prompts = _prompts(cfg, [4, 7, 5, 6])
        budgets = 8
    if case == "recovery":
        kw["fault_injector"] = FaultInjector(
            schedule=[FaultSpec("decode_dispatch", 2)]
        )

    (rids, res, srv), evs = _run_with_sink(
        tmp_path, lambda: _serve(params, cfg, prompts, budgets, **kw)
    )
    assert set(res) == set(rids)
    traces = [e for e in evs if e.get("name") == "request_trace"]
    assert sorted(e["rid"] for e in traces) == sorted(rids)  # exactly one
    for e in traces:
        assert e["outcome"] == "completed"
        assert e["wall_s"] > 0
        total = sum(e[f"{p}_s"] for p in PHASES)
        assert abs(total - e["wall_s"]) <= 0.05 * e["wall_s"] + 1e-4, (
            case, e)
        assert abs(e["attributed_s"] - total) <= 1e-3
        assert e["tokens"] == len(res[e["rid"]])
        # Decode happened for every completed request, at full tp here.
        assert e["decode_s"] > 0 and e["decode_degraded_s"] == 0.0
        # Everything joins the server's trace id.
        assert e["trace"] == srv.stats()["trace"]
    st = srv.stats()
    assert st["request_traces"] == len(traces)
    if case == "preemption":
        assert st["preemptions"] >= 1
        assert any(e["preempted_s"] > 0 for e in traces)
    if case == "recovery":
        assert st["recoveries"] >= 1
        assert any(e["recovery_s"] > 0 for e in traces)
        assert any(e["replays"] > 0 or e["recovery_s"] > 0 for e in traces)
    if case == "chunked":
        assert st["sched_chunks"] >= 1
        # Chunked slices (and their deferrals) are prefill phase.
        assert all(e["prefill_s"] > 0 for e in traces)


def test_queue_phase_dominates_under_pressure(model, tmp_path):
    """Sanity of the numbers themselves: with 2 lanes and 6 requests,
    late submitters spend real time queued — their queue_s must be a
    visible fraction of wall, and early requests' queue_s near zero."""
    cfg, params = model
    prompts = _prompts(cfg, [6, 6, 6, 6, 6, 6])
    (rids, res, srv), evs = _run_with_sink(
        tmp_path, lambda: _serve(params, cfg, prompts, 10)
    )
    traces = sorted(
        (e for e in evs if e.get("name") == "request_trace"),
        key=lambda e: e["rid"],
    )
    assert traces[-1]["queue_s"] > traces[0]["queue_s"]
    assert traces[-1]["queue_s"] > 0


def test_greedy_outputs_bit_identical_tracing_on_off(model, tmp_path):
    """Telemetry must never touch numerics: the same burst with the
    JSONL sink + flight recorder armed and with both disarmed produces
    bit-identical greedy tokens (the ledger itself is always on — it is
    host arithmetic outside every traced computation)."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 9, 6, 12], seed=5)

    prev_rec = flight.set_default_recorder(flight.FlightRecorder())
    try:
        (rids_on, res_on, _s), _evs = _run_with_sink(
            tmp_path, lambda: _serve(params, cfg, prompts, 10)
        )
    finally:
        flight.set_default_recorder(prev_rec)

    prev_sink = obs.set_default_sink(None)
    prev_rec = flight.set_default_recorder(None)
    try:
        rids_off, res_off, _s2 = _serve(params, cfg, prompts, 10)
    finally:
        obs.set_default_sink(prev_sink)
        flight.set_default_recorder(prev_rec)

    for a, b in zip(rids_on, rids_off):
        np.testing.assert_array_equal(res_on[a], res_off[b])


# ----- trace-context propagation (tentpole a) -------------------------------


def test_server_adopts_daemon_trace_ctx(model, tmp_path, monkeypatch):
    cfg, params = model
    monkeypatch.setenv("KATA_TPU_TRACE_CTX", "deadbeefcafe0123")
    prompts = _prompts(cfg, [4, 6])
    (rids, res, srv), evs = _run_with_sink(
        tmp_path, lambda: _serve(params, cfg, prompts, 6)
    )
    assert srv.stats()["trace"] == "deadbeefcafe0123"
    serving_evs = [e for e in evs if e.get("kind") == "serving"]
    assert serving_evs and all(
        e.get("trace") == "deadbeefcafe0123" for e in serving_evs
    )
    # Spans join the same trace: the guest's prefill/decode spans carry
    # the daemon's allocation trace id end to end.
    spans = [e for e in evs if e.get("kind") == "span"
             and e.get("name", "").startswith("serving.")]
    assert spans and all(e["trace"] == "deadbeefcafe0123" for e in spans)


def test_server_mints_trace_without_env(model, tmp_path, monkeypatch):
    cfg, params = model
    monkeypatch.delenv("KATA_TPU_TRACE_CTX", raising=False)
    srv_a = GenerationServer(params, cfg, max_batch=1, max_len=32)
    srv_b = GenerationServer(params, cfg, max_batch=1, max_len=32)
    ta, tb = srv_a.stats()["trace"], srv_b.stats()["trace"]
    assert ta and tb and ta != tb  # per-server join keys, never shared


def test_allocator_injects_trace_ctx_env():
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.config import Config
    from kata_xpu_device_plugin_tpu.discovery.tpu import (
        TpuChip,
        TpuInventory,
    )
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator
    from kata_xpu_device_plugin_tpu.topology.slice import HostTopology

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),
               TpuChip(index=1, dev_path="/dev/accel1")),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    alloc = TpuAllocator(lambda: inv, "google.com", "tpu", revalidate=alive)
    # Inside a gRPC handler span the stamped id IS the span's trace id —
    # the daemon-side half of the end-to-end join.
    with obs.span("plugin.Allocate", resource="google.com/tpu") as sp:
        resp = alloc.allocate(["0", "1"])
    assert resp.envs[C.ENV_TRACE_CTX] == sp.trace_id
    # Outside any span: a fresh id per allocation, still a join key.
    a = alloc.allocate(["0"]).envs[C.ENV_TRACE_CTX]
    b = alloc.allocate(["1"]).envs[C.ENV_TRACE_CTX]
    assert a and b and a != b
    # The daemon knob: --no-trace-context removes the stamp entirely.
    off = TpuAllocator(lambda: inv, "google.com", "tpu", revalidate=alive,
                       trace_context=False).allocate(["0"])
    assert C.ENV_TRACE_CTX not in off.envs
    assert Config(trace_context=False).trace_context is False
    assert Config().trace_context is True


# ----- satellite: recovery/degrade/fatal events carry trace ids -------------


def test_recovery_vocabulary_carries_trace(model, tmp_path):
    """The PR 10 incident vocabulary — fault_injected, recovery,
    request_failed — joins the allocation trace (the satellite: today
    only spans attached trace ids)."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    inj = FaultInjector(schedule=[FaultSpec("decode_dispatch", 1)])
    (rids, res, srv), evs = _run_with_sink(
        tmp_path,
        lambda: _serve(params, cfg, prompts, 8, fault_injector=inj),
    )
    trace = srv.stats()["trace"]
    recov = [e for e in evs if e.get("name") == "recovery"]
    assert recov and all(e["trace"] == trace for e in recov)
    # The injected injector has no trace of its own; the recovery event
    # stream still joins through the server's emits. An env-built
    # injector adopts the server trace:
    srv2 = GenerationServer(params, cfg, max_batch=1, max_len=32)
    assert srv2._inj.trace == srv2.stats()["trace"]


def test_chip_loss_fatal_carries_trace_and_dumps_flight(
        model, tmp_path, monkeypatch):
    """The acceptance path end to end: a chip loss with no degraded rung
    (tp=1) emits chip_loss_fatal + request_failed — all carrying the
    allocation trace — and the always-armed flight recorder dumps a
    postmortem JSONL containing the fatal event's trace id."""
    cfg, params = model
    dump_dir = tmp_path / "dumps"
    monkeypatch.setenv(flight.ENV_DIR, str(dump_dir))
    monkeypatch.setenv("KATA_TPU_TRACE_CTX", "a11ocfeedc0ffee1")
    rec = flight.FlightRecorder(capacity=64)
    prev_rec = flight.set_default_recorder(rec)
    try:
        inj = FaultInjector(
            schedule=[FaultSpec("decode_dispatch", 1, "chip_loss", 0)]
        )
        (rids, res, srv), evs = _run_with_sink(
            tmp_path,
            lambda: _serve(params, cfg, _prompts(cfg, [4, 6]), 8,
                           fault_injector=inj),
        )
    finally:
        flight.set_default_recorder(prev_rec)
    fatal = [e for e in evs if e.get("name") == "chip_loss_fatal"]
    failed = [e for e in evs if e.get("name") == "request_failed"]
    assert fatal and fatal[0]["trace"] == "a11ocfeedc0ffee1"
    assert failed and all(
        e["trace"] == "a11ocfeedc0ffee1" for e in failed
    )
    # Failed requests still close their ledgers (outcome=failed).
    traces = [e for e in evs if e.get("name") == "request_trace"]
    assert {e["rid"] for e in traces} == set(rids)
    assert all(e["outcome"] == "failed" for e in traces)
    assert srv.failures()
    # The flight dump: produced, in the configured dir, joinable.
    assert rec.dumps and os.path.dirname(rec.dumps[0]) == str(dump_dir)
    dump = _events(rec.dumps[0])
    dumped_fatal = [e for e in dump if e.get("name") == "chip_loss_fatal"]
    assert dumped_fatal and dumped_fatal[0]["trace"] == "a11ocfeedc0ffee1"


def test_clean_run_produces_no_flight_dump(model, tmp_path):
    cfg, params = model
    rec = flight.FlightRecorder(capacity=64)
    prev_rec = flight.set_default_recorder(rec)
    try:
        (rids, res, srv), _evs = _run_with_sink(
            tmp_path, lambda: _serve(params, cfg, _prompts(cfg, [4, 6]), 6)
        )
    finally:
        flight.set_default_recorder(prev_rec)
    assert set(res) == set(rids)
    assert rec.dumps == []
    assert rec.snapshot()  # armed: the run's events are in the ring


def test_fatal_error_event_on_nonrecoverable(model, tmp_path, monkeypatch):
    """A non-recoverable exception unwinds the loop but leaves evidence:
    one serving/fatal_error event — the flight recorder's guest-side
    trigger for 'the supervisor could not help'."""
    cfg, params = model
    monkeypatch.setenv("KATA_TPU_RECOVERY", "0")
    rec = flight.FlightRecorder(capacity=32)
    prev_rec = flight.set_default_recorder(rec)

    def run():
        inj = FaultInjector(schedule=[FaultSpec("decode_dispatch", 1)])
        with pytest.raises(Exception):
            _serve(params, cfg, _prompts(cfg, [4]), 8, fault_injector=inj)

    try:
        _out, evs = _run_with_sink(tmp_path, run)
    finally:
        flight.set_default_recorder(prev_rec)
    fatal = [e for e in evs if e.get("name") == "fatal_error"]
    assert len(fatal) == 1 and "TransientFault" in fatal[0]["error"]
    assert rec.dumps  # the ring dumped on it


# ----- stats schema + scheduler estimate reset ------------------------------


def test_stats_request_phase_schema(model):
    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=1, max_len=32)
    st = srv.stats()
    assert st["request_traces"] == 0
    assert set(st["request_phase_s"]) == set(PHASES)
    assert all(v == {"count": 0} for v in st["request_phase_s"].values())
    srv.submit(np.arange(4, dtype=np.int32) % cfg.vocab_size, 4)
    srv.run()
    st = srv.stats()
    assert st["request_traces"] == 1
    assert st["request_phase_s"]["decode"]["count"] == 1
    assert st["request_phase_s"]["preempted"] == {"count": 0}


def test_scheduler_reset_estimates():
    from kata_xpu_device_plugin_tpu.guest.scheduler import (
        SLOChunkedScheduler,
    )

    s = SLOChunkedScheduler(chunk_tokens=4, slo_ms=50.0, decode_steps=2)
    s.note_prefill(16, 0.08)
    s.note_round(0.02)
    assert s.projected_itl_s(32) is not None
    s.reset_estimates()  # post-shrink: old-mesh timings are stale
    assert s.projected_itl_s(32) is None
    assert s.directive(live_lanes=2, pending_tokens=64).admit  # bootstrap
