"""Continuous-batching generation server (guest/serving.py).

Oracle: greedy continuous batching is a SCHEDULING optimization — every
request's tokens must equal a lone ``generate()`` run of that prompt,
regardless of batching order, slot assignment, or queue pressure.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer, serve_batch
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    generate,
    init_params,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=1):
    key = jax.random.PRNGKey(seed)
    out = []
    for i, n in enumerate(lengths):
        out.append(np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (n,), 0, cfg.vocab_size
        ), np.int32))
    return out


def _oracle(params, cfg, prompt, steps, max_len):
    return np.asarray(
        generate(params, jnp.asarray(prompt)[None, :], cfg, steps,
                 max_len=max_len)
    )[0]


def test_single_request_matches_generate(model):
    cfg, params = model
    (p,) = _prompts(cfg, [7])
    out = serve_batch(params, cfg, [p], max_new_tokens=12,
                      max_batch=2, max_len=32)
    np.testing.assert_array_equal(out[0], _oracle(params, cfg, p, 12, 32))


def test_ragged_prompts_match_generate_per_request(model):
    cfg, params = model
    prompts = _prompts(cfg, [3, 9, 5, 12])
    out = serve_batch(params, cfg, prompts, max_new_tokens=10,
                      max_batch=4, max_len=32)
    for p, o in zip(prompts, out):
        np.testing.assert_array_equal(o, _oracle(params, cfg, p, 10, 32))


def test_queue_pressure_slot_reuse(model, tmp_path):
    # 6 requests through 2 slots: finished slots must be refilled and the
    # refilled sequences must not be corrupted by their predecessors' cache.
    # Admission must stay FIFO (the deque queue): the per-admission ttft
    # events record rids in the order slots were granted. With <= 2 free
    # slots per pass, bucket grouping cannot reorder within a pass, so the
    # event order here must be STRICTLY sorted; the general guarantee —
    # each pass admits the FIFO prefix, grouping only within it — is
    # locked by test_serving_pipeline.py's interleaved-bucket test.
    from kata_xpu_device_plugin_tpu import obs

    cfg, params = model
    sink = obs.EventSink(str(tmp_path / "events.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        prompts = _prompts(cfg, [4, 8, 6, 3, 10, 5], seed=2)
        out = serve_batch(params, cfg, prompts, max_new_tokens=8,
                          max_batch=2, max_len=32, chunk=4)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    for p, o in zip(prompts, out):
        np.testing.assert_array_equal(o, _oracle(params, cfg, p, 8, 32))
    admitted = [
        ev["rid"] for ev in obs.read_events(str(tmp_path / "events.jsonl"))
        if ev.get("name") == "ttft" and not ev.get("replay")
        # replay ttfts (crash-recovery re-admissions under a chaos
        # schedule, `make chaos`) are labeled and excluded: the FIFO
        # contract is on FIRST admission order.
    ]
    assert admitted == sorted(admitted), (
        f"admission order {admitted} violates FIFO"
    )


def test_differing_budgets_and_chunk_boundary(model):
    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=5)
    prompts = _prompts(cfg, [4, 6], seed=3)
    r0 = srv.submit(prompts[0], max_new_tokens=1)   # satisfied by prefill
    r1 = srv.submit(prompts[1], max_new_tokens=13)  # not a chunk multiple
    res = srv.run()
    assert len(res[r0]) == 1
    assert len(res[r1]) == 13
    np.testing.assert_array_equal(res[r0], _oracle(params, cfg, prompts[0], 1, 32))
    np.testing.assert_array_equal(res[r1], _oracle(params, cfg, prompts[1], 13, 32))


def test_eos_stops_early(model):
    cfg, params = model
    (p,) = _prompts(cfg, [6], seed=4)
    ref = _oracle(params, cfg, p, 16, 32)
    eos = int(ref[3])  # force a stop after the 4th generated token
    out = serve_batch(params, cfg, [p], max_new_tokens=16,
                      max_batch=1, max_len=32, eos_id=eos)
    stop = int(np.where(ref == eos)[0][0])
    np.testing.assert_array_equal(out[0], ref[: stop + 1])
    assert out[0][-1] == eos


def test_sampling_runs_and_respects_budget(model):
    cfg, params = model
    prompts = _prompts(cfg, [5, 7], seed=5)
    out = serve_batch(params, cfg, prompts, max_new_tokens=9, max_batch=2,
                      max_len=32, temperature=0.9, top_k=8, seed=42)
    assert all(len(o) == 9 for o in out)
    assert all(o.dtype == np.int32 for o in out)


def test_tensor_parallel_serving_matches_single_device(model):
    # The same server over an 8-device data×fsdp×model mesh: params placed
    # by PARAM_RULES, KV arena head-sharded over model. Deterministic CPU
    # mesh + fixed seeds → outputs must equal the single-device run.
    from kata_xpu_device_plugin_tpu.parallel import build_mesh

    cfg, params = model
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    prompts = _prompts(cfg, [4, 9, 6], seed=6)
    ref = serve_batch(params, cfg, prompts, max_new_tokens=8,
                      max_batch=2, max_len=32)
    out = serve_batch(params, cfg, prompts, max_new_tokens=8,
                      max_batch=2, max_len=32, mesh=mesh)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_mesh_serving_fused_int8_lora_layouts_match_single_device(model):
    # The production serving shape — tensor parallel × fused × int8 (the
    # BASELINE north star), plus a live-LoRA variant — must emit exactly
    # the tokens the same params produce on one device: sharding a
    # concatenated axis or a QTensor's (q, scale) pair is a layout
    # decision, never a numerics one.
    from kata_xpu_device_plugin_tpu.ops.lora import apply_lora
    from kata_xpu_device_plugin_tpu.ops.quant import quantize_decoder_params
    from kata_xpu_device_plugin_tpu.models.transformer import fuse_decoder_params
    from kata_xpu_device_plugin_tpu.parallel import build_mesh

    cfg, params = model
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    prompts = _prompts(cfg, [5, 8, 3], seed=11)
    layouts = {
        "fused": fuse_decoder_params(params),
        "fused_int8": quantize_decoder_params(fuse_decoder_params(params)),
        "lora": apply_lora(params, jax.random.PRNGKey(7), rank=2),
        "qlora_fused": apply_lora(
            quantize_decoder_params(fuse_decoder_params(params)),
            jax.random.PRNGKey(7), rank=2, targets=("wqkv", "wo"),
        ),
    }
    for name, p in layouts.items():
        ref = serve_batch(p, cfg, prompts, max_new_tokens=8,
                          max_batch=2, max_len=32)
        out = serve_batch(p, cfg, prompts, max_new_tokens=8,
                          max_batch=2, max_len=32, mesh=mesh)
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o, r, err_msg=f"layout {name}")


def test_ring_kv_serving_matches_full_cache_arena():
    # Per-slot ring arena (ring_kv=True): ragged continuous batching on a
    # sliding-window config must emit exactly the tokens the full-length
    # arena produces, while the arena holds only `window` slots — bounded
    # KV memory on long streams (VERDICT r3 weak #7: the lockstep-only
    # ring blocked this).
    from kata_xpu_device_plugin_tpu.models import mistral_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import init_kv_caches

    cfg = mistral_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(4), cfg, dtype=jnp.float32)
    # Ragged: different prompt lengths and budgets so slots sit at
    # different positions and wrap their rings at different times.
    prompts = _prompts(cfg, [5, 11, 3, 8], seed=21)
    budgets = [17, 9, 21, 13]  # all push well past window=8

    def run(**kw):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=64,
                               chunk=4, **kw)
        rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
        res = srv.run()
        return [res[r] for r in rids], srv

    ref, srv_full = run()
    out, srv = run(ring_kv=True)
    arena_leaf = jax.tree_util.tree_leaves(srv.arena)[0]
    assert arena_leaf.shape[2] == cfg.sliding_window  # O(window), not max_len
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)

    # stats() reports the footprint the ring exists to shrink.
    assert srv.stats()["arena_bytes"] < srv_full.stats()["arena_bytes"]

    # int8 arenas compose with the per-slot ring: each k/v vector
    # quantizes identically whether it lands in a ring slot or the full
    # arena, so the combination is bit-exact against int8-full-cache.
    ref_q, _ = run(kv_quant=True)
    out_q, srv_q = run(ring_kv=True, kv_quant=True)
    q_leaf = jax.tree_util.tree_leaves(srv_q.arena)[0]
    assert q_leaf.dtype == jnp.int8 and q_leaf.shape[2] == cfg.sliding_window
    for r, o in zip(ref_q, out_q):
        np.testing.assert_array_equal(o, r)


def test_cycle_arena_serving_gemma2_matches_full_arena():
    # Gemma-2's alternating local/global cycle under continuous batching:
    # ring_kv builds the cycle arena (local layers at window slots, global
    # layers at max_len) and must emit exactly the full-arena tokens.
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config

    cfg = gemma2_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(14), cfg, dtype=jnp.float32)
    prompts = _prompts(cfg, [4, 9, 6, 3], seed=31)
    budgets = [15, 8, 12, 18]

    def run(**kw):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=48,
                               chunk=4, **kw)
        rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
        res = srv.run()
        return [res[r] for r in rids], srv

    ref, _ = run()
    out, srv = run(ring_kv=True)
    # Local positions hold window slots, global positions max_len.
    local, glob = srv.arena[0], srv.arena[1]
    assert local[0].shape[2] == cfg.attn_windows[0]
    assert glob[0].shape[2] == 48
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_ring_kv_serving_rejects_bad_configs(model):
    cfg_plain, params = model
    with pytest.raises(ValueError, match="sliding-window"):
        GenerationServer(params, cfg_plain, ring_kv=True)


def test_ring_kv_speculative_serving_matches_plain_greedy():
    """ring_kv × speculative (VERDICT r4 next #6): bounded KV memory AND
    multi-token verify rounds compose — the windowed ring carries k
    margin slots so a verify span can never evict a key inside a live
    window. Tokens must equal the plain full-arena greedy server; the
    arena stays O(window + k)."""
    from kata_xpu_device_plugin_tpu.models import mistral_test_config

    cfg = mistral_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(4), cfg, dtype=jnp.float32)
    prompts = _prompts(cfg, [5, 11, 3, 8], seed=41)
    budgets = [17, 9, 21, 13]  # push well past window=8, ragged wrap points

    def run(**kw):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=64, **kw)
        rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
        res = srv.run()
        return [res[r] for r in rids], srv

    k = 3
    ref, _ = run(chunk=4)
    out, srv = run(ring_kv=True, speculative_k=k)
    arena_leaf = jax.tree_util.tree_leaves(srv.arena)[0]
    assert arena_leaf.shape[2] == cfg.sliding_window + k  # margin, not max_len
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    assert 0.0 <= srv.stats()["draft_acceptance"] <= 1.0


def test_cycle_arena_speculative_serving_matches_plain_greedy():
    """Gemma-2 cycle arena × speculative: local rings carry the margin,
    global layers keep max_len; tokens equal the full-arena greedy server
    — and a perfect draft composes on top (ring + draft model + cycle)."""
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config

    cfg = gemma2_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(14), cfg, dtype=jnp.float32)
    prompts = _prompts(cfg, [4, 9, 6, 3], seed=51)
    budgets = [15, 8, 12, 18]

    def run(**kw):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=48, **kw)
        rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
        res = srv.run()
        return [res[r] for r in rids], srv

    k = 2
    ref, _ = run(chunk=4)
    out, srv = run(ring_kv=True, speculative_k=k)
    local = srv.arena[0]
    assert local[0].shape[2] == cfg.attn_windows[0] + k
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)

    # Full composition: ring arena + DRAFT MODEL speculation.
    out_d, srv_d = run(ring_kv=True, speculative_k=k, draft=(params, cfg))
    for r, o in zip(ref, out_d):
        np.testing.assert_array_equal(o, r)
    assert srv_d.stats()["draft_acceptance"] == 1.0


def test_bucketed_prefill_is_exact(model):
    # Right-padding to buckets must not change a single token: causal
    # masking hides pads from prompt tokens, and decode's index mask never
    # reads a pad entry before overwriting it.
    cfg, params = model
    prompts = _prompts(cfg, [3, 9, 5, 12, 8], seed=7)
    ref = serve_batch(params, cfg, prompts, max_new_tokens=10,
                      max_batch=2, max_len=32)
    out = serve_batch(params, cfg, prompts, max_new_tokens=10,
                      max_batch=2, max_len=32, prefill_buckets=(4, 16))
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    # Prompt longer than every bucket: falls back to exact-length prefill.
    out2 = serve_batch(params, cfg, prompts, max_new_tokens=10,
                       max_batch=2, max_len=32, prefill_buckets=(4,))
    for r, o in zip(ref, out2):
        np.testing.assert_array_equal(o, r)
    with pytest.raises(ValueError, match="buckets"):
        GenerationServer(params, cfg, max_len=32, prefill_buckets=(64,))


def test_prefill_true_len_matches_exact(model):
    from kata_xpu_device_plugin_tpu.models.transformer import prefill

    cfg, params = model
    (p,) = _prompts(cfg, [6], seed=8)
    caches_e, last_e, pos_e = prefill(params, jnp.asarray(p)[None], cfg, 24,
                                      return_logits=True)
    padded = np.pad(p, (0, 10))
    caches_b, last_b, pos_b = prefill(params, jnp.asarray(padded)[None], cfg,
                                      24, return_logits=True,
                                      true_len=jnp.int32(len(p)))
    assert int(pos_b) == int(pos_e) == len(p)
    np.testing.assert_allclose(np.asarray(last_b), np.asarray(last_e),
                               rtol=1e-6)
    # Cache entries for the real tokens are identical; pad entries differ
    # but sit at indices the decode mask hides until overwritten.
    for ce, cb in zip(caches_e, caches_b):
        np.testing.assert_allclose(
            np.asarray(ce[:, :, : len(p)]), np.asarray(cb[:, :, : len(p)]),
            rtol=1e-6,
        )


def test_speculative_serving_matches_plain_greedy(model):
    # speculative_k changes only the SCHEDULE (verify rounds instead of
    # decode chunks): results must equal the plain greedy server — and thus
    # the per-request generate() oracle — under queue pressure and slot
    # reuse, for both accept-friendly (repetitive) and random prompts.
    cfg, params = model
    rep = np.tile(np.array([5, 17, 3], np.int32), 4)
    prompts = _prompts(cfg, [4, 9, 6], seed=9) + [rep]
    ref = serve_batch(params, cfg, prompts, max_new_tokens=9,
                      max_batch=2, max_len=32)
    out = serve_batch(params, cfg, prompts, max_new_tokens=9,
                      max_batch=2, max_len=32, speculative_k=3)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_speculative_serving_eos_and_budget(model):
    cfg, params = model
    (p,) = _prompts(cfg, [5], seed=10)
    ref = _oracle(params, cfg, p, 16, 32)
    eos = int(ref[4])
    out = serve_batch(params, cfg, [p], max_new_tokens=16, max_batch=1,
                      max_len=32, eos_id=eos, speculative_k=4)
    stop = int(np.where(ref == eos)[0][0])
    np.testing.assert_array_equal(out[0], ref[: stop + 1])
    # Tight budget: a verify round can overshoot; output must trim exactly.
    out2 = serve_batch(params, cfg, [p], max_new_tokens=2, max_batch=1,
                       max_len=32, speculative_k=4)
    np.testing.assert_array_equal(out2[0], ref[:2])


def test_stats_counters(model):
    cfg, params = model
    rep = np.tile(np.array([5, 17], np.int32), 6)
    srv = GenerationServer(params, cfg, max_batch=1, max_len=40,
                           speculative_k=3)
    srv.submit(rep, max_new_tokens=10)
    srv.run()
    st = srv.stats()
    assert st["tokens_emitted"] >= 10
    assert st["prefills"] == 1
    assert st["slots_busy"] == 0 and st["queued"] == 0
    # Edge: a request satisfied entirely by its prefill token still counts.
    srv0 = GenerationServer(params, cfg, max_batch=1, max_len=40)
    srv0.submit(rep, max_new_tokens=1)
    srv0.run()
    assert srv0.stats()["tokens_emitted"] == 1
    assert srv0.stats()["rounds"] == 0
    assert 0.0 <= st["draft_acceptance"] <= 1.0
    # Repetitive input must accept SOME drafts → fewer rounds than tokens.
    assert st["rounds"] < st["tokens_emitted"]
    assert st["tokens_per_round"] > 1.0
    # Plain greedy server: no acceptance key, one token per slot per round.
    srv2 = GenerationServer(params, cfg, max_batch=1, max_len=40, chunk=4)
    srv2.submit(rep, max_new_tokens=8)
    srv2.run()
    st2 = srv2.stats()
    assert "draft_acceptance" not in st2
    assert st2["tokens_emitted"] >= 8


def test_speculative_serving_sampling_contract(model):
    """r5: plain temperature sampling now composes with speculation (the
    lossless rejection scheme); only top_k/top_p truncation — which the
    acceptance math does not model — is rejected."""
    cfg, params = model
    GenerationServer(params, cfg, temperature=0.7, speculative_k=3)  # ok
    with pytest.raises(ValueError, match="top_k/top_p"):
        GenerationServer(params, cfg, temperature=0.7, top_p=0.9,
                         speculative_k=3)


def test_draft_model_serving_matches_plain_greedy(model):
    """Draft-MODEL speculative serving (VERDICT r4 weak #4): a depth-
    truncated self-draft proposes via its own arena; results must equal
    the plain greedy server under queue pressure and slot reuse, and the
    acceptance rate must be reported."""
    from kata_xpu_device_plugin_tpu.models import self_draft

    cfg, params = model
    draft = self_draft(params, cfg, 1)
    prompts = _prompts(cfg, [4, 9, 6, 5], seed=11)
    ref = serve_batch(params, cfg, prompts, max_new_tokens=9,
                      max_batch=2, max_len=32)
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           speculative_k=3, draft=draft)
    rids = [srv.submit(p, 9) for p in prompts]
    results = srv.run()
    for r, rid in zip(ref, rids):
        np.testing.assert_array_equal(results[rid], r)
    st = srv.stats()
    assert 0.0 <= st["draft_acceptance"] <= 1.0


def test_draft_model_serving_perfect_draft_accepts_everything(model):
    """Target-as-draft: every draft must be accepted (acceptance == 1.0)
    and rounds collapse to ceil(tokens / (k+1)) — locks both the draft
    arena's position bookkeeping (any cache skew would reject) and the
    acceptance counters."""
    cfg, params = model
    (p,) = _prompts(cfg, [6], seed=12)
    ref = _oracle(params, cfg, p, 12, 40)
    srv = GenerationServer(params, cfg, max_batch=1, max_len=40,
                           speculative_k=3, draft=(params, cfg))
    rid = srv.submit(p, 12)
    results = srv.run()
    np.testing.assert_array_equal(results[rid], ref)
    st = srv.stats()
    assert st["draft_acceptance"] == 1.0, st
    # prefill emits 1 token; 11 decode tokens in k+1=4-token rounds → 3.
    assert st["rounds"] == 3, st


def test_draft_serving_validation(model):
    from dataclasses import replace

    cfg, params = model
    with pytest.raises(ValueError, match="speculative_k"):
        GenerationServer(params, cfg, draft=(params, cfg))
    bad = replace(cfg, vocab_size=cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="vocab"):
        GenerationServer(params, cfg, speculative_k=2, draft=(params, bad))


def test_submit_validation(model):
    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=1, max_len=16)
    with pytest.raises(ValueError):
        srv.submit(np.zeros(0, np.int32))
    with pytest.raises(ValueError):
        srv.submit(np.zeros(10, np.int32), max_new_tokens=10)  # 20 > 16
    with pytest.raises(ValueError):
        GenerationServer(params, cfg, top_k=5)  # top_k without temperature


def test_speculative_sampling_serving(model):
    """temperature>0 + speculative_k: lossless speculative SAMPLING
    (rejection scheme) — reproducible per seed, varies across seeds,
    budget respected, acceptance reported; top_k/top_p still rejected."""
    from kata_xpu_device_plugin_tpu.models import self_draft

    cfg, params = model
    draft = self_draft(params, cfg, 1)
    prompts = _prompts(cfg, [5, 8, 4], seed=61)

    def run(seed):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=40,
                               temperature=0.9, speculative_k=3,
                               draft=draft, seed=seed)
        rids = [srv.submit(p, 10) for p in prompts]
        res = srv.run()
        return [res[r] for r in rids], srv.stats()

    a, st = run(3)
    b, _ = run(3)
    c, _ = run(4)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert not all(np.array_equal(x, y) for x, y in zip(a, c))
    assert all(len(x) == 10 for x in a)
    assert 0.0 <= st["draft_acceptance"] <= 1.0

    # n-gram proposal works in sampling mode too (one-hot q).
    srv = GenerationServer(params, cfg, max_batch=2, max_len=40,
                           temperature=0.9, speculative_k=3, seed=3)
    rid = srv.submit(prompts[0], 8)
    assert len(srv.run()[rid]) == 8

    with pytest.raises(ValueError, match="top_k/top_p"):
        GenerationServer(params, cfg, temperature=0.9, top_k=5,
                         speculative_k=3)


def test_export_metrics_prometheus_gauges(model):
    """Serving stats exposed as Prometheus gauges (the guest-side
    counterpart of the daemon's metrics endpoint): values come from
    stats() at scrape time, and two servers in one process coexist via
    the server label."""
    from prometheus_client import REGISTRY, generate_latest

    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           speculative_k=2)
    lbl = srv.export_metrics()
    srv2 = GenerationServer(params, cfg, max_batch=1, max_len=32)
    lbl2 = srv2.export_metrics()
    assert lbl != lbl2

    (p,) = _prompts(cfg, [5], seed=71)
    srv.submit(p, 6)
    srv.run()
    text = generate_latest(REGISTRY).decode()
    emitted = srv.stats()["tokens_emitted"]
    assert f'kata_tpu_serving_tokens_emitted{{server="{lbl}"}} {float(emitted)}' in text
    assert f'kata_tpu_serving_queued{{server="{lbl2}"}} 0.0' in text
    assert "kata_tpu_serving_draft_acceptance" in text
