"""Crash-tolerant serving: the recovery supervisor's matrix (ISSUE 7).

Oracle — RECOVERY IS INVISIBLE IN THE OUTPUT: greedy decoding is
deterministic, so a server that loses a round to an injected fault and
rebuilds (checkpointed restore or from-the-prompt replay) must emit
tokens BIT-IDENTICAL to a fault-free run, across fault kinds ×
paged/slotted × overlap × strict. The failure surfaces that may NOT be
invisible are pinned too: quarantine after K consecutive implicated
rounds fails the poison request individually (``failures()`` +
``request_failed`` event), and a drain under load completes or fails
every submitted rid — none vanish. The injector/fence primitives
themselves are covered in tests/test_resilience.py.
"""
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest import resilience
from kata_xpu_device_plugin_tpu.guest.resilience import (
    FaultInjector,
    FaultSpec,
    wire_drain,
)
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=2):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


def _serve(params, cfg, prompts, budgets=8, injector=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("chunk", 4)
    kw.setdefault("recovery_backoff_s", 0.0)
    srv = GenerationServer(
        params, cfg,
        fault_injector=injector if injector is not None else FaultInjector(),
        **kw,
    )
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    res = srv.run()
    return [res.get(r) for r in rids], srv


def _capture(tmp_path, name="ev.jsonl"):
    sink = obs.EventSink(str(tmp_path / name))
    prev = obs.set_default_sink(sink)
    return sink, prev


def _events(tmp_path, name="ev.jsonl"):
    return obs.read_events(str(tmp_path / name))


# A schedule exercising every fault kind across the serving seams: one
# transient dispatch raise, one hang (watchdog stall), one admission
# raise, one allocation OOM. Per-seam rounds are 0-based invocations.
_CHAOS = [
    FaultSpec("decode_dispatch", 2),
    FaultSpec("fence", 1, "hang"),
    FaultSpec("prefill", 1),
    FaultSpec("pool_alloc", 1, "raise-oom"),
]


# ----- the headline matrix: recovery is bit-invisible ----------------------


@pytest.mark.parametrize("overlap", [False, True])
@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("strict", [False, True])
def test_faulted_run_bit_identical_to_clean(model, paged, overlap, strict):
    """Fault-kind × paged/slotted × overlap × strict: under the chaos
    schedule every request completes with greedy tokens bit-identical to
    a fault-free run (ISSUE 7 acceptance criterion)."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    kw = dict(overlap=overlap, strict=strict, checkpoint_rounds=2)
    if paged:
        kw.update(kv_pool_tokens=4 * 32, kv_block_size=8)
    ref, _ = _serve(params, cfg, prompts, overlap=overlap)
    out, srv = _serve(params, cfg, prompts,
                      injector=FaultInjector(_CHAOS, seed=3), **kw)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    # pool_alloc only crosses on paged servers; the other three fire
    # everywhere. Each recovery really happened (not a silent no-op).
    assert st["recoveries"] == (4 if paged else 3)
    assert st["device_stalls"] == 1
    assert st["quarantined"] == 0 and srv.failures() == {}
    assert st["checkpoints"] >= 1


def test_checkpoint_restore_bounds_the_replay(model, tmp_path):
    """With a checkpoint taken before the fault, recovery RESTORES lanes
    from host KV instead of replaying from the prompt (the recovery
    event's restored/requeued split), and output is still identical."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    ref, _ = _serve(params, cfg, prompts, budgets=12)
    sink, prev = _capture(tmp_path)
    try:
        out, srv = _serve(
            params, cfg, prompts, budgets=12,
            injector=FaultInjector([FaultSpec("decode_dispatch", 2)]),
            checkpoint_rounds=1, overlap=False,
        )
    finally:
        obs.set_default_sink(prev)
        sink.close()
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    recs = [e for e in _events(tmp_path) if e.get("name") == "recovery"]
    assert len(recs) == 1
    assert recs[0]["restored"] == 2 and recs[0]["requeued"] == 0
    ckpts = [e for e in _events(tmp_path) if e.get("name") == "checkpoint"]
    assert ckpts and any(e["lanes"] >= 1 for e in ckpts)


def test_recovery_without_checkpoint_replays_from_prompt(model, tmp_path):
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    ref, _ = _serve(params, cfg, prompts)
    sink, prev = _capture(tmp_path)
    try:
        out, srv = _serve(
            params, cfg, prompts,
            injector=FaultInjector([FaultSpec("decode_dispatch", 1)]),
            overlap=False,  # checkpoint_rounds defaults off (env unset)
        )
    finally:
        obs.set_default_sink(prev)
        sink.close()
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    assert srv.stats()["checkpoints"] == 0
    (rec,) = [e for e in _events(tmp_path) if e.get("name") == "recovery"]
    assert rec["restored"] == 0 and rec["requeued"] == 2


def test_recovery_composes_with_preemption_and_prefix_tier(model):
    """The PR 6 substrate under faults: a pool tight enough to preempt,
    plus the chaos schedule — outputs still match the clean slotted run
    and nothing is lost."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3, 5, 7])
    ref, _ = _serve(params, cfg, prompts, max_batch=3)
    out, srv = _serve(
        params, cfg, prompts, max_batch=3,
        injector=FaultInjector(_CHAOS, seed=5),
        kv_pool_tokens=32 + 3 * 8, kv_block_size=8, checkpoint_rounds=2,
    )
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    assert srv.failures() == {}


def test_unsupervised_env_kill_switch_restores_unwind(model, monkeypatch):
    """KATA_TPU_RECOVERY=0: the pre-ISSUE-7 contract — the exception
    unwinds run() instead of recovering."""
    monkeypatch.setenv("KATA_TPU_RECOVERY", "0")
    cfg, params = model
    prompts = _prompts(cfg, [4])
    with pytest.raises(resilience.TransientFault):
        _serve(params, cfg, prompts,
               injector=FaultInjector([FaultSpec("decode_dispatch", 0)]))


def test_non_recoverable_errors_propagate(model):
    """A user bug (here: a ValueError from a bad submit consumed inside
    step) must not be swallowed by the supervisor — only the recoverable
    class is caught. recoverable() itself is unit-tested; this pins the
    server wiring via an injected non-transient error."""
    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=1, max_len=32, chunk=4,
                           fault_injector=FaultInjector())

    def boom():
        raise ValueError("user bug")

    srv._inj.fire = lambda seam: boom() if seam == "prefill" else None
    srv.submit(_prompts(cfg, [4])[0], 4)
    with pytest.raises(ValueError, match="user bug"):
        srv.run()


def test_checkpoint_fault_is_supervised(model):
    """The periodic checkpoint's own device→host gather can raise
    transiently — it runs INSIDE the supervised region, so the fault
    triggers recovery instead of unwinding run() (the crash-tolerance
    machinery must not be what drops the queue)."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    ref, _ = _serve(params, cfg, prompts, budgets=10)
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4,
                           overlap=False, checkpoint_rounds=1,
                           recovery_backoff_s=0.0,
                           fault_injector=FaultInjector())
    orig, calls = srv._checkpoint, []

    def flaky():
        calls.append(None)
        if len(calls) == 1:
            raise resilience.TransientFault("checkpoint gather died")
        orig()

    srv._checkpoint = flaky
    rids = [srv.submit(p, 10) for p in prompts]
    res = srv.run()
    for r, rid in zip(ref, rids):
        np.testing.assert_array_equal(res[rid], r)
    assert srv.failures() == {} and srv.stats()["recoveries"] == 1


def test_restore_fault_falls_back_to_full_replay(model, tmp_path):
    """A recoverable fault inside the RESTORE path itself (the recovery
    after the recovery): the supervisor resets again and replays every
    survivor from its prompt — outputs still bit-identical, none
    vanish."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    ref, _ = _serve(params, cfg, prompts, budgets=12)
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               chunk=4, overlap=False, checkpoint_rounds=1,
                               recovery_backoff_s=0.0,
                               fault_injector=FaultInjector(
                                   [FaultSpec("decode_dispatch", 2)]))
        orig, calls = srv._restore_lane, []

        def flaky(b, entry):
            calls.append(None)
            if len(calls) == 1:
                raise resilience.TransientFault("restore scatter died")
            return orig(b, entry)

        srv._restore_lane = flaky
        rids = [srv.submit(p, 12) for p in prompts]
        res = srv.run()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    for r, rid in zip(ref, rids):
        np.testing.assert_array_equal(res[rid], r)
    assert srv.failures() == {}
    (rec,) = [e for e in _events(tmp_path) if e.get("name") == "recovery"]
    assert rec["restored"] == 0 and rec["requeued"] == 2


# ----- quarantine ----------------------------------------------------------


def test_quarantine_after_k_consecutive_failures(model, tmp_path):
    """A poison request (its admission faults every attempt) is failed
    individually after K consecutive implicated rounds; its batch-mates
    complete with clean outputs, and the failure surfaces through
    failures() + a request_failed event — never a silent drop."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 5])
    ref, _ = _serve(params, cfg, [prompts[1]])
    sink, prev = _capture(tmp_path)
    try:
        out, srv = _serve(
            params, cfg, prompts, budgets=[6, 8],
            injector=FaultInjector([FaultSpec("prefill", i)
                                    for i in range(3)]),
            quarantine_after=3,
        )
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert out[0] is None  # quarantined: absent from results
    np.testing.assert_array_equal(out[1], ref[0])
    fails = srv.failures()
    assert list(fails) == [0] and "TransientFault" in fails[0]
    st = srv.stats()
    assert st["quarantined"] == 1 and st["failed_requests"] == 1
    (ev,) = [e for e in _events(tmp_path)
             if e.get("name") == "request_failed"]
    assert ev["rid"] == 0 and ev["reason"] == "quarantined"


def test_survived_round_resets_implication_count(model):
    """fails is CONSECUTIVE: a request that survives a round between two
    implicated failures never reaches the threshold."""
    cfg, params = model
    prompts = _prompts(cfg, [4])
    ref, _ = _serve(params, cfg, prompts, budgets=12)
    # Two decode faults separated by clean rounds: streak never hits 2.
    out, srv = _serve(
        params, cfg, prompts, budgets=12,
        injector=FaultInjector([FaultSpec("decode_dispatch", 0),
                                FaultSpec("decode_dispatch", 2)]),
        quarantine_after=2, overlap=False,
    )
    np.testing.assert_array_equal(out[0], ref[0])
    assert srv.failures() == {} and srv.stats()["recoveries"] == 2


def test_reservation_fault_blames_the_culprit_not_lane_residents(model):
    """A fault during a reservation implicates the head-of-line request
    being reserved — still in the queue, never popped — not the innocent
    lane residents: the culprit's streak is tracked (and quarantines),
    the residents requeue unimplicated and complete bit-identically."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 5, 6])
    ref, _ = _serve(params, cfg, prompts[:2])
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4,
                           kv_pool_tokens=4 * 32, kv_block_size=8,
                           quarantine_after=2, recovery_backoff_s=0.0,
                           fault_injector=FaultInjector())
    orig, count = srv._reserve_lane_blocks, [0]

    def flaky(req, hit):
        if req.rid == 2 and count[0] < 2:
            count[0] += 1
            raise resilience.TransientFault("reservation died")
        return orig(req, hit)

    srv._reserve_lane_blocks = flaky
    rids = [srv.submit(p, 8) for p in prompts]
    res = srv.run()
    fails = srv.failures()
    assert list(fails) == [2]  # only the culprit, after 2 strikes
    assert srv.stats()["quarantined"] == 1
    for r, rid in zip(ref, rids[:2]):
        np.testing.assert_array_equal(res[rid], r)


# ----- drain ---------------------------------------------------------------


def test_drain_under_load_nothing_vanishes(model, tmp_path):
    """request_drain mid-run: in-flight lanes finish (tokens identical
    to a clean run), queued requests fail with reason=drained, submit()
    refuses new work, and every submitted rid lands in exactly one of
    results/failures()."""
    cfg, params = model
    prompts = _prompts(cfg, [4 + i % 3 for i in range(6)])
    ref, _ = _serve(params, cfg, prompts)
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               chunk=4, fault_injector=FaultInjector())
        rids = [srv.submit(p, 8) for p in prompts]
        for _ in range(2):
            srv.step()
        srv.request_drain(reason="test")
        res = srv.run()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    fails = srv.failures()
    assert sorted(list(res) + list(fails)) == sorted(rids)
    assert res and fails  # the load was real: both outcomes occurred
    for rid, toks in res.items():
        np.testing.assert_array_equal(toks, ref[rids.index(rid)])
    assert all(v.startswith("drained") for v in fails.values())
    assert srv.stats()["draining"] is True
    with pytest.raises(RuntimeError, match="draining"):
        srv.submit(prompts[0], 2)
    names = [e["name"] for e in _events(tmp_path)]
    assert "drain_begin" in names and "drain" in names
    # The final checkpoint event closes the drain.
    finals = [e for e in _events(tmp_path)
              if e.get("name") == "checkpoint" and e.get("final")]
    assert len(finals) == 1
    (done,) = [e for e in _events(tmp_path) if e.get("name") == "drain"]
    assert done["completed"] == len(res) and done["failed"] == len(fails)


def test_drain_sync_api_and_idempotence(model):
    cfg, params = model
    prompts = _prompts(cfg, [4, 5, 6])
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4,
                           fault_injector=FaultInjector())
    rids = [srv.submit(p, 6) for p in prompts]
    srv.request_drain(reason="one")
    srv.request_drain(reason="two")  # idempotent: first reason wins
    res = srv.drain(reason="three")
    assert sorted(list(res) + list(srv.failures())) == sorted(rids)
    assert "one" in list(srv.failures().values())[0]


def test_drain_completes_preempted_requests(model):
    """Work that already started includes PREEMPTED requests (spilled to
    host): a drain resumes and finishes them rather than failing them."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3, 5, 7])
    ref, _ = _serve(params, cfg, prompts, max_batch=3)
    srv = GenerationServer(params, cfg, max_batch=3, max_len=32, chunk=4,
                           fault_injector=FaultInjector(),
                           kv_pool_tokens=32 + 3 * 8, kv_block_size=8)
    rids = [srv.submit(p, 8) for p in prompts]
    # Step until someone has actually been preempted (tight pool), then
    # drain: the preempted request must still complete.
    for _ in range(30):
        if not srv.step() or srv.stats()["preemptions"]:
            break
    assert srv.stats()["preemptions"] >= 1
    srv.request_drain(reason="maint")
    res = srv.run()
    fails = srv.failures()
    assert sorted(list(res) + list(fails)) == sorted(rids)
    for rid, toks in res.items():
        np.testing.assert_array_equal(toks, ref[rids.index(rid)])


def test_fault_during_drain_still_finishes_started_work(model):
    """A recoverable fault firing MID-DRAIN requeues the in-flight lanes
    as replays — started work, which the drain gate re-admits and
    finishes bit-identically; only the never-started tail fails as
    drained."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 5, 6, 7])
    ref, _ = _serve(params, cfg, prompts, budgets=16)
    srv = GenerationServer(
        params, cfg, max_batch=2, max_len=32, chunk=4, overlap=False,
        recovery_backoff_s=0.0,
        fault_injector=FaultInjector([FaultSpec("decode_dispatch", 2)]),
    )
    rids = [srv.submit(p, 16) for p in prompts]
    for _ in range(2):  # decode crossings 0 and 1 — clean rounds
        srv.step()
    srv.request_drain(reason="test")
    res = srv.run()  # crossing 2 faults during the drain
    fails = srv.failures()
    assert srv.stats()["recoveries"] == 1
    assert sorted(list(res) + list(fails)) == sorted(rids)
    assert sorted(res) == rids[:2]  # the started lanes completed
    for rid in res:
        np.testing.assert_array_equal(res[rid], ref[rids.index(rid)])
    assert all(v.startswith("drained") for v in fails.values())


def test_wire_drain_maintenance_file_and_sigterm(model, tmp_path):
    """The production triggers: a maintenance-notice file appearing
    flips the server into draining (poll_once exercised inline), and the
    SIGTERM handler does the same while chaining the prior disposition."""
    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=1, max_len=32, chunk=4,
                           fault_injector=FaultInjector())
    notice = tmp_path / "maintenance"
    wiring = wire_drain(srv, sigterm=False, maintenance_file=str(notice),
                        poll_s=0.01)
    try:
        assert not srv.stats()["draining"]
        assert wiring.poll_once() is False
        notice.write_text("scheduled")
        assert wiring.poll_once() is True
        assert srv.stats()["draining"]
    finally:
        wiring.stop()

    srv2 = GenerationServer(params, cfg, max_batch=1, max_len=32, chunk=4,
                            fault_injector=FaultInjector())
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda *a: seen.append(a))
    try:
        with wire_drain(srv2, sigterm=True):
            os.kill(os.getpid(), signal.SIGTERM)
            assert srv2.stats()["draining"]
            assert seen  # prior handler chained
    finally:
        signal.signal(signal.SIGTERM, prev)


# ----- env knobs: daemon path + degrade contract ---------------------------


def test_env_schedule_and_seed_drive_the_default_injector(model,
                                                          monkeypatch):
    """The daemon path end-to-end: KATA_TPU_FAULTS + _SEED build the
    server's injector, the run recovers, and output matches clean."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    ref, _ = _serve(params, cfg, prompts)
    monkeypatch.setenv("KATA_TPU_FAULTS",
                       "decode_dispatch:1,fence:0:hang")
    monkeypatch.setenv("KATA_TPU_FAULTS_SEED", "11")
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4,
                           recovery_backoff_s=0.0)
    assert srv._inj.armed and srv._inj.seed == 11
    rids = [srv.submit(p, 8) for p in prompts]
    res = srv.run()
    for r, rid in zip(ref, rids):
        np.testing.assert_array_equal(res[rid], r)
    assert srv.stats()["recoveries"] == 2


def test_checkpoint_cadence_env_default_and_malformed(model, monkeypatch,
                                                      tmp_path):
    """KATA_TPU_CHECKPOINT_ROUNDS: unset → cadence 0 (off); a malformed
    node-injected value degrades with a checkpoint_disabled event and
    the server still serves (never crashes a guest)."""
    cfg, params = model
    prompts = _prompts(cfg, [4])
    srv = GenerationServer(params, cfg, max_batch=1, max_len=32, chunk=4,
                           fault_injector=FaultInjector())
    assert srv.stats()["checkpoint_rounds"] == 0

    monkeypatch.setenv("KATA_TPU_CHECKPOINT_ROUNDS", "every-so-often")
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params, cfg, max_batch=1, max_len=32,
                               chunk=4, fault_injector=FaultInjector())
        rid = srv.submit(prompts[0], 4)
        res = srv.run()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert srv.stats()["checkpoint_rounds"] == 0 and rid in res
    (ev,) = [e for e in _events(tmp_path)
             if e.get("name") == "checkpoint_disabled"]
    assert ev["reason"].startswith("bad_env:")

    monkeypatch.setenv("KATA_TPU_CHECKPOINT_ROUNDS", "4")
    srv = GenerationServer(params, cfg, max_batch=1, max_len=32, chunk=4,
                           fault_injector=FaultInjector())
    assert srv.stats()["checkpoint_rounds"] == 4


def test_checkpoint_incompatible_with_speculative(model, monkeypatch,
                                                  tmp_path):
    """Draft/speculative serving: explicit checkpoint_rounds raises; the
    env default degrades with a checkpoint_disabled event (recovery then
    uses full replay)."""
    cfg, params = model
    with pytest.raises(ValueError, match="speculative"):
        GenerationServer(params, cfg, max_batch=1, max_len=32, chunk=4,
                         speculative_k=2, checkpoint_rounds=2,
                         fault_injector=FaultInjector())
    monkeypatch.setenv("KATA_TPU_CHECKPOINT_ROUNDS", "2")
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params, cfg, max_batch=1, max_len=32,
                               chunk=4, speculative_k=2,
                               fault_injector=FaultInjector())
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert srv.stats()["checkpoint_rounds"] == 0
    (ev,) = [e for e in _events(tmp_path)
             if e.get("name") == "checkpoint_disabled"]
    assert ev["reason"] == "speculative"


def test_stats_schema_always_has_resilience_fields(model):
    """Dashboards need no schema branch: the resilience fields are
    present (zeros) on a server that never failed."""
    cfg, params = model
    _, srv = _serve(params, cfg, _prompts(cfg, [4]))
    st = srv.stats()
    for k in ("recoveries", "quarantined", "device_stalls", "checkpoints",
              "checkpoint_rounds", "failed_requests", "draining"):
        assert k in st
    assert st["recoveries"] == 0 and st["draining"] is False


def test_allocator_injects_resilience_env(tmp_path):
    """The daemon path: config.checkpoint_rounds / config.faults land in
    the TPU AllocateResponse env like the compile/prefix/pool knobs."""
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.discovery.tpu import TpuChip, TpuInventory
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator
    from kata_xpu_device_plugin_tpu.topology.slice import HostTopology

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive,
        checkpoint_rounds=8, fault_schedule="decode_dispatch:3",
    ).allocate(["0"])
    assert wired.envs[C.ENV_CHECKPOINT_ROUNDS] == "8"
    assert wired.envs[C.ENV_FAULT_SCHEDULE] == "decode_dispatch:3"
    # Defaults: neither knob set → neither env injected.
    bare = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive
    ).allocate(["0"])
    assert C.ENV_CHECKPOINT_ROUNDS not in bare.envs
    assert C.ENV_FAULT_SCHEDULE not in bare.envs
