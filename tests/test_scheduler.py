"""SLO-aware prefill/decode scheduler (guest/scheduler.py, ISSUE 8).

Oracle — SCHEDULING IS INVISIBLE IN THE OUTPUT: the scheduler only
decides WHEN prefill work runs and in what slice sizes, never what the
forwards compute, so greedy outputs under ``slo_chunked`` must be
BIT-IDENTICAL to the ``fifo_batch`` baseline across paged/slotted ×
overlap × strict × prefix-hit. The visible surfaces are pinned separately:
the policy objects' deferral math, the env/daemon knob degrade contract
(``sched_disabled`` events, never a crashed guest), the ``sched_defer`` /
``slo_violation`` event stream, strict-FIFO preservation, mid-chunk crash
replay (the PR 7 none-vanish guarantee through the new ``sched_tick``
seam), the speculative opt-in demotion (``spec_disabled``), and the
allocator env injection.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest.resilience import (
    FaultInjector,
    FaultSpec,
)
from kata_xpu_device_plugin_tpu.guest.scheduler import (
    DEFAULT_PREFILL_CHUNK,
    POLICY_FIFO,
    POLICY_SLO,
    Directive,
    Scheduler,
    SLOChunkedScheduler,
    make_scheduler,
)
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    init_kv_caches,
    init_params,
    prefill,
    prefill_suffix,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=3):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


# Staggered budgets: equal ones synchronize lane finishes, so admissions
# would always run against an idle arena (live=0 → the policy admits
# whole) and chunking would never engage.
_LENS = [14, 9, 12, 7, 15, 11]
_BUDGETS = [6, 12, 9, 5, 11, 7]


def _serve(params, cfg, policy, *, injector=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("recovery_backoff_s", 0.0)
    if policy == POLICY_SLO:
        # slo_ms=0 forces deferral the moment estimates exist — the
        # deterministic maximal-chunking configuration.
        kw.setdefault("prefill_chunk", 4)
        kw.setdefault("itl_slo_ms", 0.0)
    if injector is not None:
        kw["fault_injector"] = injector
    # No explicit injector → the env default (FaultInjector.from_env):
    # disarmed in a plain run, and under `make chaos` the node schedule
    # (incl. sched_tick) fires HERE — recovery must stay invisible in
    # every assertion below.
    srv = GenerationServer(params, cfg, sched_policy=policy, **kw)
    prompts = _prompts(cfg, _LENS)
    rids = [srv.submit(p, m) for p, m in zip(prompts, _BUDGETS)]
    res = srv.run()
    return [res[r] for r in rids], srv


def _events(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def _capture(tmp_path, name="ev.jsonl"):
    sink = obs.EventSink(str(tmp_path / name))
    return sink, obs.set_default_sink(sink)


# ----- policy objects (host-side unit surface) -------------------------------


def test_fifo_policy_always_admits_and_reports_zeros():
    s = Scheduler(label="t")
    assert s.directive(live_lanes=3, pending_tokens=4096).admit
    assert s.note_round(10.0) is False  # no SLO → never a violation
    st = s.stats()
    assert st["sched_policy"] == POLICY_FIFO
    assert st["sched_chunks"] == 0 and st["sched_defers"] == 0
    assert st["slo_violations"] == 0 and st["itl_slo_ms"] == 0.0


def test_slo_policy_deferral_math():
    s = SLOChunkedScheduler(chunk_tokens=64, slo_ms=50.0, label="t")
    # Bootstrap: no estimates yet → admit whole (measure first).
    assert s.directive(live_lanes=2, pending_tokens=1024).admit
    # Prime: 1024 tokens at 0.1 ms/token, rounds at 10 ms (under SLO).
    s.note_prefill(1000, 0.1)
    assert s.note_round(0.010) is False
    # Nobody decoding → nothing to protect → admit.
    assert s.directive(live_lanes=0, pending_tokens=4096).admit
    # Small admission (≤ one chunk) → slicing cannot help → admit.
    assert s.directive(live_lanes=2, pending_tokens=64).admit
    # 1024 tokens ≈ 102 ms prefill + 10 ms round ≫ 50 ms SLO → defer.
    d = s.directive(live_lanes=2, pending_tokens=1024)
    assert not d.admit and d.defer_reason == "projected_itl"
    assert d.projected_itl_ms > 50.0
    # 256 tokens ≈ 26 ms + 10 ms < 50 ms → admit whole.
    assert s.directive(live_lanes=1, pending_tokens=256).admit
    # A partial keeps deferring below chunk size (continue-vs-one-more-
    # chunk, never skip): remaining 32 < chunk 64 but still over SLO? No
    # — 32 tokens ≈ 3 ms + 10 ms < 50 → completes whole.
    assert s.directive(live_lanes=2, pending_tokens=32, partial=True).admit


def test_slo_policy_violation_counting():
    s = SLOChunkedScheduler(chunk_tokens=8, slo_ms=5.0)
    assert s.note_round(0.004) is False
    assert s.note_round(0.006) is True
    assert s.note_round(0.0) is False  # ignored, not a violation
    assert s.slo_violations == 1


def test_slo_policy_per_token_normalization():
    # slo_ms is a PER-TOKEN deadline (the decode_token_s unit): a server
    # whose rounds deliver decode_steps tokens per lane divides the round
    # cadence before comparing — a 16-step round taking 32 ms is 2
    # ms/token, NOT a 32 ms violation of a 5 ms SLO.
    s = SLOChunkedScheduler(chunk_tokens=8, slo_ms=5.0, decode_steps=16)
    assert s.note_round(0.032) is False  # 2 ms/token < 5 ms
    assert s.note_round(0.160) is True   # 10 ms/token > 5 ms
    # The EWMA tracks PER-TOKEN cadence (ISSUE 13 satellite — the old
    # code EWMA'd the raw round cadence and divided by a STATIC
    # decode_steps at projection time, misprojecting the moment the
    # delivered tokens-per-dispatch differ from the configured count).
    assert s._tok_s == pytest.approx(0.002 + 0.3 * (0.010 - 0.002))
    # The projection amortizes the prefill stall over the round's
    # delivered tokens and adds the per-token cadence.
    s.note_prefill(1000, 0.1)  # 0.1 ms/token prefill rate
    proj = s.projected_itl_s(1600)
    assert proj == pytest.approx(1600 * 0.0001 / 16 + s._tok_s)
    # And the deferral decision uses the normalized figure: 1600 tokens
    # project ~14 ms/token (defer), 16 tokens ~4.5 ms (admit).
    assert not s.directive(live_lanes=2, pending_tokens=1600).admit
    assert s.directive(live_lanes=2, pending_tokens=16).admit


def test_note_round_tracks_actual_steps():
    # ISSUE 13 satellite: note_round learns the ACTUAL tokens-per-
    # dispatch — a fused or multi-step round passes its delivered count
    # and both the violation check and the projection divisor follow it,
    # not the configured default.
    s = SLOChunkedScheduler(chunk_tokens=8, slo_ms=5.0, decode_steps=4)
    assert s.note_round(0.032, steps=16) is False  # 2 ms/token at K×chunk
    assert s._last_steps == 16
    s.note_prefill(1000, 0.1)
    assert s.projected_itl_s(1600) == pytest.approx(
        1600 * 0.0001 / 16 + 0.002
    )
    # Fewer steps delivered → the same wall time violates.
    assert s.note_round(0.032, steps=4) is True  # 8 ms/token > 5 ms
    assert s._last_steps == 4


def test_note_config_resets_estimates_on_regime_change():
    # ISSUE 13 satellite: a changed decode_steps K or fused-plan flag
    # invalidates the per-round timings — note_config drops the EWMAs so
    # the first post-change round re-measures; an unchanged config keeps
    # them.
    s = SLOChunkedScheduler(chunk_tokens=8, slo_ms=5.0, decode_steps=4)
    s.note_prefill(1000, 0.1)
    s.note_round(0.02)
    assert s._tok_s is not None
    assert s.note_config(decode_steps=4, fused=False) is False
    assert s._tok_s is not None  # unchanged config keeps estimates
    assert s.note_config(decode_steps=8) is True
    assert s._tok_s is None and s._prefill_s_per_tok is None
    assert s._last_steps == 8
    s.note_round(0.02)
    assert s.note_config(fused=True) is True
    assert s._tok_s is None


def test_make_scheduler_rejects_unknown_policy():
    assert isinstance(
        make_scheduler(POLICY_FIFO, chunk_tokens=0, slo_ms=0.0), Scheduler
    )
    assert isinstance(
        make_scheduler(POLICY_SLO, chunk_tokens=8, slo_ms=1.0),
        SLOChunkedScheduler,
    )
    with pytest.raises(ValueError, match="policy"):
        make_scheduler("round_robin", chunk_tokens=8, slo_ms=1.0)
    with pytest.raises(ValueError, match="chunk"):
        SLOChunkedScheduler(chunk_tokens=0)
    assert Directive(admit=True).defer_reason == ""


# ----- the oracle: chunking is invisible in greedy output --------------------


@pytest.mark.parametrize("overlap", [True, False])
@pytest.mark.parametrize("paged", [True, False])
def test_chunked_greedy_identity(model, overlap, paged):
    cfg, params = model
    extra = {"kv_pool_tokens": 160} if paged else {}
    base, _ = _serve(params, cfg, POLICY_FIFO, overlap=overlap, **extra)
    out, srv = _serve(params, cfg, POLICY_SLO, overlap=overlap, **extra)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    st = srv.stats()
    assert st["sched_policy"] == POLICY_SLO
    assert st["sched_chunks"] > 0, "chunking never engaged — dead A/B"
    assert st["sched_defers"] > 0


def test_chunked_greedy_identity_strict(model):
    cfg, params = model
    base, _ = _serve(params, cfg, POLICY_FIFO, strict=True)
    out, srv = _serve(params, cfg, POLICY_SLO, strict=True)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["sched_chunks"] > 0


def test_chunked_prefix_hit_identity(model):
    # Chunking composes with the prefix store: a hit materializes the
    # shared rows, then the SUFFIX chunks from the match boundary.
    cfg, params = model
    key = jax.random.PRNGKey(9)
    shared = np.asarray(
        jax.random.randint(key, (8,), 0, cfg.vocab_size), np.int32
    )
    tails = _prompts(cfg, [4] * 6, seed=10)
    prompts = [np.concatenate([shared, t]) for t in tails]

    def run(policy):
        srv = GenerationServer(
            params, cfg, max_batch=2, max_len=32, chunk=4,
            prefill_buckets=(4, 8, 12), prefix_cache_tokens=64,
            sched_policy=policy, prefill_chunk=3, itl_slo_ms=0.0,
            fault_injector=FaultInjector(),
        )
        rids = [srv.submit(p, m) for p, m in zip(prompts, _BUDGETS)]
        res = srv.run()
        return [res[r] for r in rids], srv

    base, _ = run(POLICY_FIFO)
    out, srv = run(POLICY_SLO)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    st = srv.stats()
    assert st["sched_chunks"] > 0 and st["prefix_hits"] > 0


def test_chunk_slices_match_single_prefill(model):
    # The transformer-level contract the server path rides: chained
    # prefill_suffix slices over fresh caches reproduce the single-call
    # prefill — same greedy next token, same cache rows.
    cfg, params = model
    (prompt,) = _prompts(cfg, [13], seed=11)
    max_len = 32
    full, f_last, f_pos = prefill(
        params, jnp.asarray(prompt)[None, :], cfg, max_len,
        return_logits=True,
    )
    caches = init_kv_caches(cfg, 1, max_len)
    off = 0
    for c in (5, 5, 5):  # 13 tokens in 5+5+3 slices, last padded to 5
        take = min(c, len(prompt) - off)
        sl = prompt[off:off + take]
        if take < c:
            sl = np.pad(sl, (0, c - take))
        caches, last, pos = prefill_suffix(
            params, jnp.asarray(sl)[None, :], cfg, caches, jnp.int32(off),
            return_logits=True, true_len=jnp.int32(take),
        )
        off += take
    assert off == len(prompt) and int(pos) == int(f_pos)
    assert int(jnp.argmax(last)) == int(jnp.argmax(f_last))
    for a, b in zip(jax.tree_util.tree_leaves(full),
                    jax.tree_util.tree_leaves(caches)):
        np.testing.assert_allclose(
            np.asarray(a)[:, :, :len(prompt)],
            np.asarray(b)[:, :, :len(prompt)], atol=1e-5,
        )


# ----- FIFO / events / drain -------------------------------------------------


def test_chunked_preserves_fifo_and_emits_events(model, tmp_path):
    cfg, params = model
    sink, prev = _capture(tmp_path)
    try:
        out, srv = _serve(params, cfg, POLICY_SLO)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evs = _events(tmp_path / "ev.jsonl")
    ttft = [e for e in evs if e.get("name") == "ttft"]
    # ≤ 2 free lanes per pass and chunked admission is head-of-line, so
    # FIRST admission grants must be strictly FIFO (crash-recovery
    # replays re-emit ttft labeled replay=n — filtered, per the PR 7
    # contract, so the assertion also holds under `make chaos`).
    rids = [e["rid"] for e in ttft if not e.get("replay")]
    assert rids == sorted(rids)
    defers = [e for e in evs if e.get("name") == "sched_defer"]
    assert defers, "no sched_defer events despite forced chunking"
    for e in defers:
        assert {"rid", "offset", "remaining", "queued",
                "slo_ms"} <= set(e)
    # Chunked admissions label their ttft event with the slice count.
    assert any(e.get("chunked", 0) > 1 for e in ttft)
    st = srv.stats()
    assert st["sched_defers"] == len(defers)
    # slo_ms=0 → every retired round violates; events mirror the counter.
    viol = [e for e in evs if e.get("name") == "slo_violation"]
    assert st["slo_violations"] == len(viol) > 0
    # >= not ==: a chaos-schedule replay re-grants admission.
    assert st["sched_queue_delay_s"]["count"] >= len(_LENS)


def test_mid_chunk_fault_replays_from_prompt(model, tmp_path):
    # The ISSUE 8 × ISSUE 7 composition: a fault at the sched_tick seam
    # (a chunk boundary) loses the half-prefilled partial; recovery must
    # replay it FROM THE PROMPT, strict-FIFO, with outputs bit-identical
    # to the fault-free run — and the replayed admission's ttft event
    # says so.
    cfg, params = model
    base, _ = _serve(params, cfg, POLICY_SLO)
    sink, prev = _capture(tmp_path)
    try:
        out, srv = _serve(
            params, cfg, POLICY_SLO,
            injector=FaultInjector([FaultSpec("sched_tick", 2)], seed=7),
        )
    finally:
        obs.set_default_sink(prev)
        sink.close()
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    st = srv.stats()
    assert st["recoveries"] == 1
    assert not srv.failures()
    evs = _events(tmp_path / "ev.jsonl")
    assert any(e.get("name") == "fault_injected"
               and e.get("seam") == "sched_tick" for e in evs)
    assert any(e.get("name") == "ttft" and e.get("replay") for e in evs)


def test_chunked_drain_none_vanish(model):
    cfg, params = model
    srv = GenerationServer(
        params, cfg, max_batch=2, max_len=32, chunk=4,
        prefill_buckets=(16,), sched_policy=POLICY_SLO, prefill_chunk=4,
        itl_slo_ms=0.0, fault_injector=FaultInjector(),
    )
    prompts = _prompts(cfg, _LENS)
    rids = [srv.submit(p, m) for p, m in zip(prompts, _BUDGETS)]
    # A few rounds in (a partial may be mid-flight), then drain: every
    # rid must end in exactly one of results/failures — none vanish,
    # and started work (including a partial) finishes.
    for _ in range(3):
        srv.step()
    srv.request_drain("test")
    results = srv.run()
    seen = set(results) | set(srv.failures())
    assert seen == set(rids)
    for rid, toks in results.items():
        assert len(toks) > 0


# ----- knob contract ---------------------------------------------------------


def test_env_policy_selection(model, monkeypatch):
    cfg, params = model
    monkeypatch.setenv("KATA_TPU_SCHED_POLICY", "slo_chunked")
    monkeypatch.setenv("KATA_TPU_PREFILL_CHUNK", "6")
    monkeypatch.setenv("KATA_TPU_ITL_SLO_MS", "7.5")
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           prefill_buckets=(16,))
    st = srv.stats()
    assert st["sched_policy"] == POLICY_SLO
    assert st["prefill_chunk_tokens"] == 6
    assert st["itl_slo_ms"] == 7.5


def test_env_unknown_policy_degrades_with_event(model, monkeypatch,
                                                tmp_path):
    cfg, params = model
    monkeypatch.setenv("KATA_TPU_SCHED_POLICY", "round_robin")
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert srv.stats()["sched_policy"] == POLICY_FIFO
    (ev,) = [e for e in _events(tmp_path / "ev.jsonl")
             if e.get("name") == "sched_disabled"]
    assert ev["reason"].startswith("bad_env:round_robin")


def test_explicit_unknown_policy_raises(model):
    cfg, params = model
    with pytest.raises(ValueError, match="sched_policy"):
        GenerationServer(params, cfg, sched_policy="round_robin")


def test_env_malformed_knobs_degrade(model, monkeypatch, tmp_path):
    cfg, params = model
    monkeypatch.setenv("KATA_TPU_SCHED_POLICY", "slo_chunked")
    monkeypatch.setenv("KATA_TPU_PREFILL_CHUNK", "128k")
    monkeypatch.setenv("KATA_TPU_ITL_SLO_MS", "fast")
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params, cfg, max_batch=2, max_len=512,
                               prefill_buckets=(16,))
    finally:
        obs.set_default_sink(prev)
        sink.close()
    st = srv.stats()
    # Malformed values fall back to the defaults, policy survives.
    assert st["sched_policy"] == POLICY_SLO
    assert st["prefill_chunk_tokens"] == DEFAULT_PREFILL_CHUNK
    assert st["itl_slo_ms"] > 0
    names = {e.get("name") for e in _events(tmp_path / "ev.jsonl")}
    assert {"prefill_chunk_invalid", "itl_slo_invalid"} <= names
    # A parseable-but-nonsense chunk (< 1 token) degrades the same way.
    monkeypatch.setenv("KATA_TPU_PREFILL_CHUNK", "-5")
    srv2 = GenerationServer(params, cfg, max_batch=2, max_len=512,
                            prefill_buckets=(16,))
    assert srv2.stats()["prefill_chunk_tokens"] == DEFAULT_PREFILL_CHUNK


def test_incompatible_modes_raise_or_degrade(model, monkeypatch, tmp_path):
    cfg2 = tiny_test_config(dtype=jnp.float32, sliding_window=8)
    params2 = init_params(jax.random.PRNGKey(0), cfg2, dtype=jnp.float32)
    # Explicit slo_chunked on a ring server: refuse loudly.
    with pytest.raises(ValueError, match="slo_chunked"):
        GenerationServer(params2, cfg2, ring_kv=True,
                         sched_policy="slo_chunked")
    # Env-selected on the same server: degrade with the reason.
    monkeypatch.setenv("KATA_TPU_SCHED_POLICY", "slo_chunked")
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params2, cfg2, ring_kv=True)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert srv.stats()["sched_policy"] == POLICY_FIFO
    (ev,) = [e for e in _events(tmp_path / "ev.jsonl")
             if e.get("name") == "sched_disabled"]
    assert ev["reason"] == "ring_kv"


def test_incompatible_speculative_raises(model):
    cfg, params = model
    with pytest.raises(ValueError, match="slo_chunked"):
        GenerationServer(params, cfg, speculative_k=3, spec_opt_in=True,
                         sched_policy="slo_chunked")


def test_explicit_bad_chunk_raises(model):
    cfg, params = model
    with pytest.raises(ValueError, match="chunk"):
        GenerationServer(params, cfg, sched_policy="slo_chunked",
                         prefill_chunk=0)
    # Unconditional: explicit nonsense raises whatever the policy — even
    # fifo_batch (where the knob is unused) or an env-selected policy
    # must not silently swallow a caller's typo.
    with pytest.raises(ValueError, match="chunk"):
        GenerationServer(params, cfg, prefill_chunk=0)


def test_stats_schema_always_has_sched_fields(model):
    cfg, params = model
    out, srv = _serve(params, cfg, POLICY_FIFO)
    st = srv.stats()
    for k in ("sched_policy", "sched_chunks", "sched_defers",
              "slo_violations", "prefill_chunk_tokens", "itl_slo_ms",
              "sched_queue_delay_s"):
        assert k in st
    assert st["sched_policy"] == POLICY_FIFO
    assert st["sched_chunks"] == 0 and st["slo_violations"] == 0
    assert st["sched_queue_delay_s"]["count"] >= len(_LENS)


def test_sched_prom_counters_exported(model):
    from prometheus_client import generate_latest
    from prometheus_client import REGISTRY

    cfg, params = model
    out, srv = _serve(params, cfg, POLICY_SLO)
    label = srv.export_metrics()
    text = generate_latest(REGISTRY).decode()
    assert "kata_tpu_serving_prefill_chunks_total" in text
    assert "kata_tpu_serving_admission_defers_total" in text
    assert "kata_tpu_serving_itl_slo_violations_total" in text
    # The stem differs from the scrape gauge (sched_chunks): the
    # factory adopts <name>_total, so a gauge/counter pair may not
    # share a stem (the kv_preemptions/preemptions precedent).
    assert f'kata_tpu_serving_prefill_chunks_total{{server="{label}"}}' in text


# ----- speculative demotion (ISSUE 8 satellite) ------------------------------


def test_spec_disabled_by_default(model, monkeypatch, tmp_path):
    # Without the opt-in (conftest sets KATA_TPU_SPEC=1 suite-wide; this
    # test pins the real-world DEFAULT), speculative_k degrades to plain
    # decoding with a spec_disabled event — and the outputs equal the
    # plain greedy server's, because the spec path is simply not taken.
    cfg, params = model
    monkeypatch.setenv("KATA_TPU_SPEC", "0")
    sink, prev = _capture(tmp_path)
    try:
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               chunk=4, speculative_k=3,
                               fault_injector=FaultInjector())
    finally:
        obs.set_default_sink(prev)
        sink.close()
    assert srv.speculative_k == 0 and srv.draft is None
    assert "draft_acceptance" not in srv.stats()
    (ev,) = [e for e in _events(tmp_path / "ev.jsonl")
             if e.get("name") == "spec_disabled"]
    assert ev["reason"] == "opt_in_required"
    assert ev["speculative_k"] == 3
    prompts = _prompts(cfg, [7, 5])
    rids = [srv.submit(p, 8) for p in prompts]
    res = srv.run()
    plain = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4,
                             fault_injector=FaultInjector())
    prids = [plain.submit(p, 8) for p in prompts]
    pres = plain.run()
    for r, p in zip(rids, prids):
        np.testing.assert_array_equal(res[r], pres[p])


def test_spec_opt_in_env_and_arg(model, monkeypatch):
    cfg, params = model
    # Env opt-in (the suite's conftest default): spec stays armed.
    monkeypatch.setenv("KATA_TPU_SPEC", "1")
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                           speculative_k=2)
    assert srv.speculative_k == 2
    # Explicit arg overrides a disabled env in both directions.
    monkeypatch.setenv("KATA_TPU_SPEC", "0")
    srv2 = GenerationServer(params, cfg, max_batch=2, max_len=32,
                            speculative_k=2, spec_opt_in=True)
    assert srv2.speculative_k == 2
    monkeypatch.setenv("KATA_TPU_SPEC", "1")
    srv3 = GenerationServer(params, cfg, max_batch=2, max_len=32,
                            speculative_k=2, spec_opt_in=False)
    assert srv3.speculative_k == 0
    # Invalid spec configs still refuse loudly BEFORE the opt-in gate.
    with pytest.raises(ValueError, match="speculative_k"):
        GenerationServer(params, cfg, draft=(params, cfg))


# ----- daemon plumbing -------------------------------------------------------


def test_allocator_injects_sched_env():
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.discovery.tpu import (
        TpuChip,
        TpuInventory,
    )
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator
    from kata_xpu_device_plugin_tpu.topology.slice import HostTopology

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive,
        sched_policy="slo_chunked", prefill_chunk=256, itl_slo_ms=40.0,
    ).allocate(["0"])
    assert wired.envs[C.ENV_SCHED_POLICY] == "slo_chunked"
    assert wired.envs[C.ENV_PREFILL_CHUNK] == "256"
    assert wired.envs[C.ENV_ITL_SLO_MS] == "40.0"
    # Defaults: no knob set → no env injected.
    bare = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive
    ).allocate(["0"])
    for key in (C.ENV_SCHED_POLICY, C.ENV_PREFILL_CHUNK, C.ENV_ITL_SLO_MS):
        assert key not in bare.envs


def test_config_validates_sched_knobs():
    from kata_xpu_device_plugin_tpu.config import Config

    assert Config(sched_policy="slo_chunked", prefill_chunk=128,
                  itl_slo_ms=50.0).sched_policy == "slo_chunked"
    assert Config().sched_policy == ""
    with pytest.raises(ValueError, match="sched-policy"):
        Config(sched_policy="round_robin")
    with pytest.raises(ValueError, match="prefill-chunk"):
        Config(prefill_chunk=-1)
    with pytest.raises(ValueError, match="itl-slo-ms"):
        Config(itl_slo_ms=-0.5)
