"""The DCN bridge's TRUE path: two local processes, plugin-style env,
a real ``jax.distributed.initialize`` rendezvous, and one cross-process
psum (VERDICT r3 missing #4 — every earlier test stopped at ``resolve()``).

This is the TPU-native equivalent of the reference's only cross-process
transport (its kubelet gRPC, generic_device_plugin.go:200-219): the plugin
injects ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` via CDI env edits, and
the guest turns them into a process group. Here each "host" is a local CPU
process with one virtual device; worker 0 doubles as the coordinator,
exactly as ``resolve()`` derives it.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

_CHILD = """
import json, os
import jax
# Belt and braces with JAX_PLATFORMS=cpu: plugin backends (the remote-TPU
# axon tunnel) ignore the env var, and initializing one that is unreachable
# hangs the child inside a native call (same pin as tests/conftest.py).
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from kata_xpu_device_plugin_tpu.guest.distributed import initialize_from_env

summary = initialize_from_env(port=int(os.environ["TEST_COORD_PORT"]))
assert summary["initialized"], summary
# Multi-controller collective: each process contributes its local device's
# value; psum must return the global sum (1 + 2 = 3) on BOTH sides.
pid = summary["process_id"]
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.full((jax.local_device_count(), 2), float(pid + 1))
)
print("RESULT " + json.dumps({"summary": summary, "psum": out[0].tolist()}))
"""

# Simulated 2-host rung: 2 processes × 2 virtual devices = a 4-device dp
# group spanning a process (DCN) boundary. One data-parallel SGD step on a
# least-squares objective: each device grads its own shard, psum averages
# across ALL FOUR devices, every replica applies the same update. The
# resulting weights must match the single-process closed computation.
_CHILD_DP = """
import json, os
import jax
jax.config.update("jax_platforms", "cpu")  # see _CHILD: axon ignores the env var
import jax.numpy as jnp
from kata_xpu_device_plugin_tpu.guest.distributed import initialize_from_env

summary = initialize_from_env(port=int(os.environ["TEST_COORD_PORT"]))
pid = summary["process_id"]
n_local = jax.local_device_count()
n_global = jax.device_count()
assert (n_local, n_global) == (2, 4), (n_local, n_global)

D, LR = 8, 0.1
w0 = jnp.zeros((D,), jnp.float32)

def grad_shard(w, x, y):          # per-device shard gradient (sum, not mean)
    err = x @ w - y
    return x.T @ err

def dp_step(w, x, y):
    g = jax.lax.psum(grad_shard(w, x, y), "dp")   # crosses the DCN boundary
    return w - LR * g / 16.0                       # 4 shards x 4 rows

# Deterministic global data: 16 rows split 4 per device; this process owns
# shards [2*pid, 2*pid+1].
key = jax.random.PRNGKey(0)
X = jax.random.normal(key, (16, D), jnp.float32)
Y = jax.random.normal(jax.random.fold_in(key, 1), (16,), jnp.float32)
rows = X.reshape(4, 4, D)[2 * pid : 2 * pid + 2]
ys = Y.reshape(4, 4)[2 * pid : 2 * pid + 2]
w = jax.pmap(dp_step, axis_name="dp", in_axes=(None, 0, 0))(w0, rows, ys)
print("RESULT " + json.dumps({"pid": pid, "w": w[0].tolist()}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_jax_distributed_psum():
    # No pytest-timeout in the image: _run_pair's communicate(timeout=) is
    # the hang bound — a stuck barrier fails the test instead of wedging CI.
    # The env (TPU_WORKER_ID + ordered TPU_WORKER_HOSTNAMES) is exactly
    # what the plugin's CDI edits inject (topology.runtime_env).
    port, results = _run_pair(
        _CHILD, {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    )

    for wid, res in results.items():
        s = res["summary"]
        assert s["num_processes"] == 2 and s["process_id"] == wid
        assert s["coordinator_address"] == f"localhost:{port}"
        assert s["global_devices"] == 2 and s["local_devices"] == 1
        # 1 (worker 0) + 2 (worker 1) summed across the process boundary.
        assert res["psum"] == [3.0, 3.0], (wid, res)


def _run_pair(child: str, extra_env: dict) -> tuple[int, dict]:
    port = _free_port()
    procs = []
    for wid in (0, 1):
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "TPU_WORKER_ID": str(wid),
                "TPU_WORKER_HOSTNAMES": "localhost,localhost",
                "TEST_COORD_PORT": str(port),
                **extra_env,
            }
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = {}
    for wid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=570)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"worker {wid} hung (barrier/coordinator failure)")
        assert proc.returncode == 0, f"worker {wid} failed:\n{err[-2000:]}"
        line = next(l for l in out.splitlines() if l.startswith("RESULT "))
        results[wid] = json.loads(line[len("RESULT "):])
    return port, results


def test_simulated_two_host_data_parallel_step():
    """2 processes × 2 virtual devices: one dp SGD step whose gradient psum
    crosses the simulated DCN boundary; both hosts must land on the exact
    weights of the single-process reference (VERDICT r3 next #8)."""
    _port, results = _run_pair(
        _CHILD_DP, {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    )

    import jax
    import jax.numpy as jnp
    import numpy as np

    D, LR = 8, 0.1
    key = jax.random.PRNGKey(0)
    X = np.asarray(jax.random.normal(key, (16, D), jnp.float32))
    Y = np.asarray(jax.random.normal(jax.random.fold_in(key, 1), (16,), jnp.float32))
    w_ref = -LR * (X.T @ (X @ np.zeros(D, np.float32) - Y)) / 16.0

    w0, w1 = results[0]["w"], results[1]["w"]
    np.testing.assert_allclose(w0, w1, rtol=0, atol=0)  # replicas agree
    np.testing.assert_allclose(w0, w_ref, rtol=1e-5, atol=1e-6)
