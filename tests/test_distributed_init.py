"""The DCN bridge's TRUE path: two local processes, plugin-style env,
a real ``jax.distributed.initialize`` rendezvous, and one cross-process
psum (VERDICT r3 missing #4 — every earlier test stopped at ``resolve()``).

This is the TPU-native equivalent of the reference's only cross-process
transport (its kubelet gRPC, generic_device_plugin.go:200-219): the plugin
injects ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` via CDI env edits, and
the guest turns them into a process group. Here each "host" is a local CPU
process with one virtual device; worker 0 doubles as the coordinator,
exactly as ``resolve()`` derives it.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

# The spawned workers import the package, whose compat layer normalizes
# jax's RNG-partitioning config (jax_threefry_partitionable) — which on
# 0.4.x CHANGES the threefry stream. Import it here too so the parent's
# closed-form references are computed from the same stream the workers
# drew their data from.
from kata_xpu_device_plugin_tpu.compat import jaxapi as _jaxapi  # noqa: F401

_CHILD = """
import json, os
import jax
# Belt and braces with JAX_PLATFORMS=cpu: plugin backends (the remote-TPU
# axon tunnel) ignore the env var, and initializing one that is unreachable
# hangs the child inside a native call (same pin as tests/conftest.py).
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from kata_xpu_device_plugin_tpu.guest.distributed import initialize_from_env

summary = initialize_from_env(port=int(os.environ["TEST_COORD_PORT"]))
assert summary["initialized"], summary
# Multi-controller collective: each process contributes its local device's
# value; psum must return the global sum (1 + 2 = 3) on BOTH sides.
pid = summary["process_id"]
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.full((jax.local_device_count(), 2), float(pid + 1))
)
print("RESULT " + json.dumps({"summary": summary, "psum": out[0].tolist()}))
"""

# Simulated 2-host rung: 2 processes × 2 virtual devices = a 4-device dp
# group spanning a process (DCN) boundary. One data-parallel SGD step on a
# least-squares objective: each device grads its own shard, psum averages
# across ALL FOUR devices, every replica applies the same update. The
# resulting weights must match the single-process closed computation.
_CHILD_DP = """
import json, os
import jax
jax.config.update("jax_platforms", "cpu")  # see _CHILD: axon ignores the env var
import jax.numpy as jnp
from kata_xpu_device_plugin_tpu.guest.distributed import initialize_from_env

summary = initialize_from_env(port=int(os.environ["TEST_COORD_PORT"]))
pid = summary["process_id"]
n_local = jax.local_device_count()
n_global = jax.device_count()
assert (n_local, n_global) == (2, 4), (n_local, n_global)

D, LR = 8, 0.1
w0 = jnp.zeros((D,), jnp.float32)

def grad_shard(w, x, y):          # per-device shard gradient (sum, not mean)
    err = x @ w - y
    return x.T @ err

def dp_step(w, x, y):
    g = jax.lax.psum(grad_shard(w, x, y), "dp")   # crosses the DCN boundary
    return w - LR * g / 16.0                       # 4 shards x 4 rows

# Deterministic global data: 16 rows split 4 per device; this process owns
# shards [2*pid, 2*pid+1].
key = jax.random.PRNGKey(0)
X = jax.random.normal(key, (16, D), jnp.float32)
Y = jax.random.normal(jax.random.fold_in(key, 1), (16,), jnp.float32)
rows = X.reshape(4, 4, D)[2 * pid : 2 * pid + 2]
ys = Y.reshape(4, 4)[2 * pid : 2 * pid + 2]
w = jax.pmap(dp_step, axis_name="dp", in_axes=(None, 0, 0))(w0, rows, ys)
print("RESULT " + json.dumps({"pid": pid, "w": w[0].tolist()}))
"""


# The real thing (VERDICT r4 missing #2): ``parallel.make_train_step`` — the
# GSPMD step itself, not a hand-rolled pmap — over a mesh whose fsdp axis
# SPANS the process boundary (2 procs × 2 local devices, fsdp=4). This is
# the BASELINE configs[4] software shape (v5p-16: one mesh across Kata pods,
# gradient/all-gather traffic over DCN) at miniature scale. Each process
# feeds only its addressable batch shard (make_array_from_callback); the
# loss and a post-update parameter fingerprint are replicated outputs, so
# both controllers must print identical values — which the parent then
# checks against the SAME mesh shape run in one process.
_CHILD_GSPMD = """
import json, os
import jax
jax.config.update("jax_platforms", "cpu")  # see _CHILD: axon ignores the env var
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from kata_xpu_device_plugin_tpu.guest.distributed import initialize_from_env
from kata_xpu_device_plugin_tpu import parallel
from kata_xpu_device_plugin_tpu.models import llama3_train_test

summary = initialize_from_env(port=int(os.environ["TEST_COORD_PORT"]))
assert (jax.local_device_count(), jax.device_count()) == (2, 4)

cfg = llama3_train_test()
mesh = parallel.build_mesh({"data": 1, "fsdp": 4, "model": 1})
init_state, step = parallel.make_train_step(cfg, mesh)
state = init_state(jax.random.PRNGKey(0))

tokens_np = (np.arange(8 * 33, dtype=np.int32) % cfg.vocab_size).reshape(8, 33)
sharding = NamedSharding(mesh, parallel.batch_spec(mesh))
tokens = jax.make_array_from_callback(
    tokens_np.shape, sharding, lambda idx: tokens_np[idx]
)
state, loss = step(state, tokens)

# Replicated scalar fingerprint of the updated params: the sum reduces over
# fsdp-sharded leaves, so XLA's psum crosses the DCN boundary to produce it.
fp = jax.jit(
    lambda p: sum(jnp.sum(jnp.abs(x.astype(jnp.float32))) for x in jax.tree.leaves(p)),
    out_shardings=NamedSharding(mesh, jax.sharding.PartitionSpec()),
)(state["params"])
print("RESULT " + json.dumps({
    "pid": summary["process_id"],
    "loss": float(loss),
    "fingerprint": float(fp),
    "step": int(state["step"]),
}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_jax_distributed_psum():
    # No pytest-timeout in the image: _run_pair's communicate(timeout=) is
    # the hang bound — a stuck barrier fails the test instead of wedging CI.
    # The env (TPU_WORKER_ID + ordered TPU_WORKER_HOSTNAMES) is exactly
    # what the plugin's CDI edits inject (topology.runtime_env).
    port, results = _run_pair(
        _CHILD, {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    )

    for wid, res in results.items():
        s = res["summary"]
        assert s["num_processes"] == 2 and s["process_id"] == wid
        assert s["coordinator_address"] == f"localhost:{port}"
        assert s["global_devices"] == 2 and s["local_devices"] == 1
        # 1 (worker 0) + 2 (worker 1) summed across the process boundary.
        assert res["psum"] == [3.0, 3.0], (wid, res)


def _run_pair(child: str, extra_env: dict) -> tuple[int, dict]:
    port = _free_port()
    procs = []
    for wid in (0, 1):
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "TPU_WORKER_ID": str(wid),
                "TPU_WORKER_HOSTNAMES": "localhost,localhost",
                "TEST_COORD_PORT": str(port),
                **extra_env,
            }
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = {}
    for wid, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=570)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"worker {wid} hung (barrier/coordinator failure)")
        assert proc.returncode == 0, f"worker {wid} failed:\n{err[-2000:]}"
        line = next(l for l in out.splitlines() if l.startswith("RESULT "))
        results[wid] = json.loads(line[len("RESULT "):])
    return port, results


def test_simulated_two_host_data_parallel_step():
    """2 processes × 2 virtual devices: one dp SGD step whose gradient psum
    crosses the simulated DCN boundary; both hosts must land on the exact
    weights of the single-process reference (VERDICT r3 next #8)."""
    _port, results = _run_pair(
        _CHILD_DP, {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    )

    import jax
    import jax.numpy as jnp
    import numpy as np

    D, LR = 8, 0.1
    key = jax.random.PRNGKey(0)
    X = np.asarray(jax.random.normal(key, (16, D), jnp.float32))
    Y = np.asarray(jax.random.normal(jax.random.fold_in(key, 1), (16,), jnp.float32))
    w_ref = -LR * (X.T @ (X @ np.zeros(D, np.float32) - Y)) / 16.0

    w0, w1 = results[0]["w"], results[1]["w"]
    np.testing.assert_allclose(w0, w1, rtol=0, atol=0)  # replicas agree
    np.testing.assert_allclose(w0, w_ref, rtol=1e-5, atol=1e-6)


def test_gspmd_train_step_across_process_boundary():
    """``make_train_step`` with fsdp=4 spanning 2 processes × 2 devices:
    loss and updated-param fingerprint must agree between the controllers
    AND match the identical mesh shape run in this single process
    (VERDICT r4 missing #2 / next #2)."""
    _port, results = _run_pair(
        _CHILD_GSPMD, {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    )

    for field in ("loss", "fingerprint", "step"):
        assert results[0][field] == results[1][field], (
            f"controllers disagree on {field}: {results}"
        )
    assert results[0]["step"] == 1

    # Single-process reference: same mesh SHAPE (fsdp=4) on 4 local devices,
    # same seed, same tokens — the program is identical GSPMD, only the
    # transport under the collectives differs, so values must match to
    # float32 reduction noise.
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from kata_xpu_device_plugin_tpu import parallel
    from kata_xpu_device_plugin_tpu.models import llama3_train_test

    cfg = llama3_train_test()
    mesh = parallel.build_mesh(
        {"data": 1, "fsdp": 4, "model": 1}, devices=jax.devices()[:4]
    )
    init_state, step = parallel.make_train_step(cfg, mesh)
    state = init_state(jax.random.PRNGKey(0))
    tokens_np = (np.arange(8 * 33, dtype=np.int32) % cfg.vocab_size).reshape(8, 33)
    state, loss = step(state, parallel.shard_batch(jnp.asarray(tokens_np), mesh))
    fp = jax.jit(
        lambda p: sum(
            jnp.sum(jnp.abs(x.astype(jnp.float32))) for x in jax.tree.leaves(p)
        ),
        out_shardings=NamedSharding(mesh, PartitionSpec()),
    )(state["params"])

    np.testing.assert_allclose(results[0]["loss"], float(loss), rtol=1e-5)
    np.testing.assert_allclose(results[0]["fingerprint"], float(fp), rtol=1e-5)
