"""Deploy-manifest semantics (VERDICT r1 item 4: the round-1 nodeSelector
`gke-tpu-accelerator: "true"` could never match a real GKE TPU node, whose
label VALUE is the accelerator type). No cluster needed — these assert the
scheduling contract of deploy/kata-tpu-device-plugin.yaml itself."""
import os
import re

import pytest
import yaml

MANIFEST = os.path.join(
    os.path.dirname(__file__), "..", "deploy", "kata-tpu-device-plugin.yaml"
)
MAKEFILE = os.path.join(os.path.dirname(__file__), "..", "Makefile")


@pytest.fixture(scope="module")
def ds():
    with open(MANIFEST) as f:
        doc = yaml.safe_load(f)
    assert doc["kind"] == "DaemonSet" and doc["apiVersion"] == "apps/v1"
    return doc


def _pod_spec(ds):
    return ds["spec"]["template"]["spec"]


def test_tpu_scheduling_uses_exists_not_boolean(ds):
    spec = _pod_spec(ds)
    # The label's value is the accelerator type — a fixed-value nodeSelector
    # on it schedules nowhere.
    assert "cloud.google.com/gke-tpu-accelerator" not in (
        spec.get("nodeSelector") or {}
    )
    terms = spec["affinity"]["nodeAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"
    ]["nodeSelectorTerms"]
    exprs = [e for t in terms for e in t["matchExpressions"]]
    tpu = [e for e in exprs if e["key"] == "cloud.google.com/gke-tpu-accelerator"]
    assert tpu and tpu[0]["operator"] == "Exists" and "values" not in tpu[0]


def test_tolerates_tpu_taint(ds):
    tolerations = _pod_spec(ds)["tolerations"]
    assert any(
        t.get("key") == "google.com/tpu" and t.get("operator") == "Exists"
        for t in tolerations
    )


def test_volume_mounts_are_backed_and_cover_plugin_needs(ds):
    spec = _pod_spec(ds)
    volumes = {v["name"]: v for v in spec["volumes"]}
    (container,) = spec["containers"]
    for m in container["volumeMounts"]:
        assert m["name"] in volumes, f"mount {m['name']} has no volume"
    host_paths = {v["hostPath"]["path"] for v in volumes.values() if "hostPath" in v}
    for needed in (
        "/var/lib/kubelet/device-plugins",
        "/var/lib/kubelet/pod-resources",
        "/dev",
        "/sys",
        "/var/run/cdi",
    ):
        assert needed in host_paths, f"plugin needs hostPath {needed}"


def test_image_tag_matches_makefile_version(ds):
    """The reference ships a Makefile/deploy tag mismatch (SURVEY Quirks 1);
    keep ours in lockstep."""
    (container,) = _pod_spec(ds)["containers"]
    tag = container["image"].rsplit(":", 1)[1]
    with open(MAKEFILE) as f:
        mk = f.read()
    version = re.search(r"^VERSION\s*:=\s*(\S+)", mk, re.M).group(1)
    assert tag == f"v{version}", (tag, version)


def test_node_name_from_downward_api(ds):
    (container,) = _pod_spec(ds)["containers"]
    env = {e["name"]: e for e in container.get("env", [])}
    assert (
        env["KATA_TPU_NODE_NAME"]["valueFrom"]["fieldRef"]["fieldPath"]
        == "spec.nodeName"
    )
