"""Multi-host coordination tests (SURVEY §4 "multi-node without a real
cluster"): several simulated hosts in one process, each with its own metadata
/ fake sysfs, must independently agree on the slice's worker ordering and
emit consistent topology env — the invariant libtpu needs across the Kata
pods of one v5p-16 slice (SURVEY §7 stage 7, hard part #3)."""
from __future__ import annotations

import os

import pytest

from kata_xpu_device_plugin_tpu.config import Config
from kata_xpu_device_plugin_tpu.discovery.sysfs import FakeSysfsBuilder
from kata_xpu_device_plugin_tpu.multihost import (
    SliceMembership,
    canonical_order,
    multislice_env,
    parse_worker_network_endpoints,
    resolve_membership,
)
from kata_xpu_device_plugin_tpu.multihost.resolver import load_state
from kata_xpu_device_plugin_tpu.plugin.manager import PluginManager, build_tpu_spec

HOSTS4 = ("t1v-n-abc-w-0", "t1v-n-abc-w-1", "t1v-n-abc-w-2", "t1v-n-abc-w-3")


# ----- pure helpers --------------------------------------------------------


def test_canonical_order_numeric_suffix():
    # Lexicographic order would put w-10 before w-2; ordinal order must not.
    hosts = [f"slice-w-{i}" for i in (10, 2, 0, 11, 1)]
    assert canonical_order(hosts) == tuple(f"slice-w-{i}" for i in (0, 1, 2, 10, 11))


def test_canonical_order_dedup_and_plain_names():
    assert canonical_order(["b", "a", "b"]) == ("a", "b")


def test_parse_worker_network_endpoints_tpu_vm_shape():
    raw = "t1v-w-0:10.130.0.9:8476, t1v-w-1:10.130.0.10:8476"
    assert parse_worker_network_endpoints(raw) == ("t1v-w-0", "t1v-w-1")


def test_parse_worker_network_endpoints_bare_ips_and_hosts():
    assert parse_worker_network_endpoints("10.0.0.1:8476,10.0.0.2") == (
        "10.0.0.1",
        "10.0.0.2",
    )
    assert parse_worker_network_endpoints("a.internal,b.internal") == (
        "a.internal",
        "b.internal",
    )


def test_multislice_env():
    assert multislice_env(1, 0, "") == {}
    env = multislice_env(4, 2, "coord:8080")
    assert env["MEGASCALE_NUM_SLICES"] == "4"
    assert env["MEGASCALE_SLICE_ID"] == "2"
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "coord:8080"
    with pytest.raises(ValueError):
        multislice_env(4, 4, "")


# ----- resolution ladder ---------------------------------------------------


def test_resolve_standalone_host_is_none(tmp_path):
    assert (
        resolve_membership({}, hostname="solo", state_dir=str(tmp_path)) is None
    )


def test_resolve_explicit_config_wins_over_env():
    mem = resolve_membership(
        {"TPU_WORKER_ID": "3", "TPU_WORKER_HOSTNAMES": "x,y,z,w"},
        hostname="h-w-1",
        explicit_worker_id=1,
        explicit_hostnames=HOSTS4,
    )
    assert mem == SliceMembership(1, HOSTS4, "config")


def test_resolve_env_is_authoritative_and_unsorted():
    # GKE sets both vars together; env order must be preserved as-is.
    mem = resolve_membership(
        {"TPU_WORKER_ID": "2", "TPU_WORKER_HOSTNAMES": "c,a,b"}, hostname="zz"
    )
    assert mem == SliceMembership(2, ("c", "a", "b"), "env")


def test_resolve_env_hostnames_without_id_derives_own_index():
    mem = resolve_membership(
        {"TPU_WORKER_HOSTNAMES": "a,b,c"}, hostname="b.cluster.local"
    )
    assert mem is not None and (mem.worker_id, mem.source) == (1, "derived")


def _write_metadata(d, endpoints, worker_number=None):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "worker-network-endpoints"), "w") as f:
        f.write(endpoints)
    if worker_number is not None:
        with open(os.path.join(d, "agent-worker-number"), "w") as f:
            f.write(str(worker_number))


def test_resolve_metadata_directory(tmp_path):
    md = tmp_path / "md"
    _write_metadata(md, ",".join(f"{h}:10.0.0.{i}:8476" for i, h in enumerate(HOSTS4)), 2)
    mem = resolve_membership({}, hostname="unrelated", metadata_dir=str(md))
    assert mem == SliceMembership(2, HOSTS4, "metadata")


def test_each_simulated_host_agrees_without_coordinator(tmp_path):
    """16 hosts, ordinals crossing 9, no worker-number attribute anywhere:
    every host derives its id purely from the shared hostname list."""
    hosts = tuple(f"pod-w-{i}" for i in range(16))
    seen = {}
    for h in sorted(hosts, reverse=True):  # resolution order must not matter
        mem = resolve_membership({}, hostname=h, explicit_hostnames=list(hosts))
        assert mem is not None and mem.hostnames == canonical_order(hosts)
        seen[h] = mem.worker_id
    assert sorted(seen.values()) == list(range(16))
    assert seen["pod-w-10"] == 10  # ordinal, not lexicographic


def test_resolve_persists_and_survives_source_loss(tmp_path):
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, ",".join(HOSTS4), 3)
    first = resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    assert first is not None and first.worker_id == 3
    assert load_state(state) is not None
    # Pod restart with the metadata agent down: identity must not change.
    again = resolve_membership({}, hostname="x", metadata_dir="", state_dir=state)
    assert again is not None
    assert (again.worker_id, again.hostnames, again.source) == (3, HOSTS4, "state")


def test_resolve_live_source_wins_over_stale_state(tmp_path):
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, ",".join(HOSTS4), 1)
    resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    _write_metadata(md, ",".join(HOSTS4[:2]), 0)  # slice recreated smaller
    mem = resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    assert mem is not None and (mem.worker_id, mem.num_hosts) == (0, 2)
    persisted = load_state(state)
    assert persisted is not None and persisted.worker_id == 0


def test_host_not_in_list_resolves_none():
    assert resolve_membership({}, hostname="stranger", explicit_hostnames=HOSTS4) is None


def test_explicit_id_preserves_operator_hostname_order():
    # Position in the operator's list IS the id assignment; never re-sort it.
    mem = resolve_membership(
        {}, hostname="x", explicit_worker_id=0, explicit_hostnames=("c", "a", "b")
    )
    assert mem == SliceMembership(0, ("c", "a", "b"), "config")


def test_explicit_id_out_of_range_is_rejected():
    mem = resolve_membership(
        {"TPU_WORKER_HOSTNAMES": "a,b"},
        hostname="b",
        explicit_worker_id=7,
        explicit_hostnames=("a", "b"),
    )
    # The flag *pair* is invalid and dropped, but the pinned id still
    # overrides the env-derived answer (operator's word is final; warned).
    assert mem is not None and mem.worker_id == 7 and mem.source == "config"


def test_explicit_id_without_hostnames_is_honored():
    mem = resolve_membership({}, hostname="x", explicit_worker_id=2)
    assert mem == SliceMembership(2, (), "config")


def test_explicit_id_overrides_env_derived_id():
    mem = resolve_membership(
        {"TPU_WORKER_HOSTNAMES": "a,b,c", "TPU_WORKER_ID": "1"},
        hostname="c",
        explicit_worker_id=2,
    )
    assert mem == SliceMembership(2, ("a", "b", "c"), "config")


def test_stale_state_discarded_when_node_repurposed(tmp_path):
    """A node pulled out of a deleted v5p-32 slice and redeployed standalone
    must not keep emitting its dead multi-host identity."""
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, ",".join(HOSTS4), 3)
    resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    assert load_state(state) is not None
    # Metadata gone AND the hardware now says single-host:
    mem = resolve_membership({}, hostname="x", state_dir=state, num_hosts_hint=1)
    assert mem is None
    assert load_state(state) is None  # cleared, not just ignored


def test_state_not_rewritten_when_unchanged(tmp_path):
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, ",".join(HOSTS4), 1)
    resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    path = os.path.join(state, "worker-identity.json")
    ino = os.stat(path).st_ino
    resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    assert os.stat(path).st_ino == ino  # os.replace would have changed it


def test_config_validates_multislice_and_worker_id(tmp_path):
    with pytest.raises(ValueError):
        Config(num_slices=4, slice_id=4)
    with pytest.raises(ValueError):
        Config(num_slices=0)
    with pytest.raises(ValueError):
        Config(worker_id=2, worker_hostnames=("a", "b"))


# ----- manager integration: a v5p-16 slice as two simulated hosts ----------


def _v5p_host(root: str) -> FakeSysfsBuilder:
    fake = FakeSysfsBuilder(root=root)
    for i in range(4):
        fake.add_accel_chip(i)
        fake.add_pci_function(f"0000:0{i}:05.0", "1ae0", "0062", numa_node=i // 2)
    return fake


def _env_dict(spec) -> dict[str, str]:
    return dict(e.split("=", 1) for e in spec.container_edits.env)


def test_v5p16_two_hosts_emit_consistent_cdi_env(tmp_path):
    """SURVEY §4's multi-node simulation: one manager per fake host, shared
    metadata content, distinct worker numbers → CDI specs whose guests can
    form one slice (same hostnames/bounds, unique ids)."""
    hostnames = ("vp-w-0", "vp-w-1")
    envs = []
    for worker in range(2):
        root = str(tmp_path / f"host{worker}")
        fake = _v5p_host(root)
        md = str(tmp_path / f"md{worker}")
        _write_metadata(md, ",".join(hostnames), worker)
        cfg = Config(
            sysfs_root=fake.sysfs,
            dev_root=fake.dev,
            cdi_dir=str(tmp_path / f"cdi{worker}"),
            accelerator_type="v5p-16",
            metadata_dir=md,
            state_dir=str(tmp_path / f"state{worker}"),
            metrics_port=0,
            libtpu_host_path="",
        )
        mgr = PluginManager(cfg)
        tpu_inv, _ = mgr.scan()
        assert tpu_inv.topology.num_hosts == 2
        envs.append(_env_dict(build_tpu_spec(tpu_inv, cfg)))

    assert envs[0]["TPU_WORKER_ID"] == "0" and envs[1]["TPU_WORKER_ID"] == "1"
    for key in ("TPU_WORKER_HOSTNAMES", "TPU_HOST_BOUNDS", "TPU_CHIPS_PER_HOST_BOUNDS",
                "TPU_ACCELERATOR_TYPE"):
        assert envs[0][key] == envs[1][key], key
    assert envs[0]["TPU_WORKER_HOSTNAMES"] == "vp-w-0,vp-w-1"
    assert envs[0]["TPU_HOST_BOUNDS"] == "1,1,2"  # v5p stacks host bricks in z


def test_autodetected_topology_scales_to_membership(tmp_path):
    """No --accelerator-type and no TPU_* env: discovery only sees 4 local
    chips (v5p device id → 'v5p-8', 1 host). A 2-host membership must scale
    the topology, not ship 2 hostnames against 1-host bounds."""
    fake = _v5p_host(str(tmp_path / "host"))
    md = str(tmp_path / "md")
    _write_metadata(md, "vp-w-0,vp-w-1", 1)
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        metadata_dir=md,
        state_dir=str(tmp_path / "state"),
        metrics_port=0,
        libtpu_host_path="",
    )
    tpu_inv, _ = PluginManager(cfg).scan()
    topo = tpu_inv.topology
    assert topo.accelerator_type == "v5p-16"
    assert (topo.num_hosts, topo.worker_id) == (2, 1)
    assert topo.host_bounds_str() == "1,1,2"


def test_authoritative_type_mismatch_fails_closed(tmp_path):
    """An explicit single-host accelerator type contradicting a 2-host
    membership must not produce a self-contradictory guest env."""
    fake = _v5p_host(str(tmp_path / "host"))
    md = str(tmp_path / "md")
    _write_metadata(md, "vp-w-0,vp-w-1", 1)
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        accelerator_type="v5p-8",  # pinned: 1 host
        metadata_dir=md,
        state_dir="",
        metrics_port=0,
        libtpu_host_path="",
    )
    tpu_inv, _ = PluginManager(cfg).scan()
    topo = tpu_inv.topology
    assert (topo.num_hosts, topo.worker_id, topo.worker_hostnames) == (1, 0, ())


def test_autodetect_outage_keeps_persisted_identity(tmp_path):
    """Metadata agent down on restart + autodetected type: num_hosts=1 from
    local chips must NOT clear the persisted 2-host identity."""
    fake = _v5p_host(str(tmp_path / "host"))
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, "vp-w-0,vp-w-1", 1)
    base = dict(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        state_dir=state,
        metrics_port=0,
        libtpu_host_path="",
    )
    PluginManager(Config(metadata_dir=md, **base)).scan()
    assert load_state(state) is not None
    import shutil

    shutil.rmtree(md)
    tpu_inv, _ = PluginManager(Config(metadata_dir=md, **base)).scan()
    topo = tpu_inv.topology
    assert (topo.worker_id, topo.worker_hostnames) == (1, ("vp-w-0", "vp-w-1"))
    assert topo.num_hosts == 2  # scaled from persisted membership


def test_from_env_bare_worker_id():
    from kata_xpu_device_plugin_tpu.multihost.resolver import from_env

    assert from_env({"TPU_WORKER_ID": "0"}) == SliceMembership(0, (), "env")
    assert from_env({}) is None


def test_from_env_unaddressable_id_drops_peer_list():
    """ADVICE r1: TPU_WORKER_ID >= len(TPU_WORKER_HOSTNAMES) is a malformed
    node env — the id stays (it answers "who am I"), the peers are dropped
    rather than propagated into the CDI spec env."""
    from kata_xpu_device_plugin_tpu.multihost.resolver import from_env

    mem = from_env({"TPU_WORKER_ID": "5", "TPU_WORKER_HOSTNAMES": "a,b"})
    assert mem == SliceMembership(5, (), "env")
    # in-range id keeps the list
    mem = from_env({"TPU_WORKER_ID": "1", "TPU_WORKER_HOSTNAMES": "a,b"})
    assert mem == SliceMembership(1, ("a", "b"), "env")


def test_hostnameless_membership_on_multihost_type_fails_closed(tmp_path):
    """ADVICE r1: a bare worker id overlaid on a multi-host accelerator type
    would give guests N-host bounds with an empty TPU_WORKER_HOSTNAMES —
    fail closed to the standalone topology instead."""
    fake = _v5p_host(str(tmp_path / "host"))
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        accelerator_type="v5p-16",  # authoritative: 2 hosts
        worker_id=1,  # pinned, but no peer list anywhere
        metrics_port=0,
        libtpu_host_path="",
    )
    tpu_inv, _ = PluginManager(cfg).scan()
    topo = tpu_inv.topology
    assert (topo.num_hosts, topo.worker_id, topo.worker_hostnames) == (1, 0, ())


def test_short_peer_list_on_multihost_type_fails_closed(tmp_path):
    """A 1-entry peer list against a 2-host type is the same contradiction
    as an empty one (its mem.num_hosts==1 slips past the count-mismatch
    guard) — must also fail closed to the standalone topology."""
    fake = _v5p_host(str(tmp_path / "host"))
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        accelerator_type="v5p-16",  # authoritative: 2 hosts
        worker_id=0,
        worker_hostnames=("hosta",),  # too short for 2 hosts
        metrics_port=0,
        libtpu_host_path="",
    )
    tpu_inv, _ = PluginManager(cfg).scan()
    topo = tpu_inv.topology
    assert (topo.num_hosts, topo.worker_id, topo.worker_hostnames) == (1, 0, ())


def test_bare_env_id_merges_metadata_hostnames(tmp_path):
    """GKE sets TPU_WORKER_ID alone on some pools; the peer list from
    metadata must still reach the guests (id stays authoritative)."""
    md = str(tmp_path / "md")
    _write_metadata(md, ",".join(HOSTS4))  # endpoints only, no worker-number
    mem = resolve_membership(
        {"TPU_WORKER_ID": "2"}, hostname="unmatched", metadata_dir=md
    )
    assert mem is not None
    assert (mem.worker_id, mem.hostnames, mem.source) == (2, HOSTS4, "env")


def test_bare_env_id_merges_persisted_hostnames_and_does_not_clobber(tmp_path):
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, ",".join(HOSTS4), 2)
    resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    import shutil

    shutil.rmtree(md)  # metadata outage; only the bare env id remains
    mem = resolve_membership(
        {"TPU_WORKER_ID": "2"}, hostname="x", metadata_dir=md, state_dir=state
    )
    assert mem is not None and mem.hostnames == HOSTS4
    persisted = load_state(state)  # complete identity must survive untouched
    assert persisted is not None and persisted.hostnames == HOSTS4


def test_authoritative_mismatch_strips_env_baked_identity(tmp_path, monkeypatch):
    """Env carries a 4-host identity that scan_tpus bakes into the topology;
    a pinned 1-host accelerator type must strip it, not half-refuse it."""
    fake = _v5p_host(str(tmp_path / "host"))
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "a,b,c,d")
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        accelerator_type="v5p-8",  # authoritative: 1 host
        state_dir="",
        metrics_port=0,
        libtpu_host_path="",
    )
    tpu_inv, _ = PluginManager(cfg).scan()
    topo = tpu_inv.topology
    assert (topo.num_hosts, topo.worker_id, topo.worker_hostnames) == (1, 0, ())
    env = _env_dict(build_tpu_spec(tpu_inv, cfg))
    assert "TPU_WORKER_HOSTNAMES" not in env and env["TPU_WORKER_ID"] == "0"


def test_partial_host_cannot_scale_to_multihost(tmp_path):
    """4 chips of an 8-chip v5e machine + a claimed 2-host membership: no
    valid topology exists — fail closed instead of inventing 'v5litepod-8'
    (which would be ONE 8-chip host, not two 4-chip ones)."""
    fake = FakeSysfsBuilder(root=str(tmp_path / "host"))
    for i in range(4):
        fake.add_accel_chip(i)
        fake.add_pci_function(f"0000:0{i}:04.0", "1ae0", "0063", numa_node=0)
    md = str(tmp_path / "md")
    _write_metadata(md, "e-w-0,e-w-1", 1)
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        metadata_dir=md,
        state_dir="",
        metrics_port=0,
        libtpu_host_path="",
    )
    tpu_inv, _ = PluginManager(cfg).scan()
    topo = tpu_inv.topology
    assert (topo.num_hosts, topo.worker_id, topo.worker_hostnames) == (1, 0, ())


def test_ip_hostname_never_short_name_matches():
    # '10.0.0.9' must not claim worker 0 of a slice listed as bare IPs.
    mem = resolve_membership(
        {}, hostname="10.0.0.9", explicit_hostnames=("10.0.0.1", "10.0.0.2")
    )
    assert mem is None
    mem = resolve_membership(
        {}, hostname="10.0.0.2", explicit_hostnames=("10.0.0.1", "10.0.0.2")
    )
    assert mem is not None and mem.worker_id == 1  # exact IP match still works


def test_explicit_flag_id_merges_metadata_peers(tmp_path):
    """--worker-id must get the same peer merge a bare env id gets."""
    md = str(tmp_path / "md")
    _write_metadata(md, ",".join(HOSTS4))  # no agent-worker-number
    mem = resolve_membership(
        {}, hostname="unmatched", explicit_worker_id=2, metadata_dir=md
    )
    assert mem is not None
    assert (mem.worker_id, mem.hostnames, mem.source) == (2, HOSTS4, "config")


def test_authoritative_refusal_rebuilds_standalone_topology(tmp_path):
    """Fail-closed must not keep multi-host bounds with worker 0 / no peers —
    the emitted env has to be self-consistent for the LOCAL chips."""
    fake = _v5p_host(str(tmp_path / "host"))
    md = str(tmp_path / "md")
    _write_metadata(md, "a,b,c,d", 1)  # 4 hosts, contradicting v5p-16 (2)
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        accelerator_type="v5p-16",
        metadata_dir=md,
        state_dir="",
        metrics_port=0,
        libtpu_host_path="",
    )
    tpu_inv, _ = PluginManager(cfg).scan()
    topo = tpu_inv.topology
    assert (topo.num_hosts, topo.worker_id, topo.worker_hostnames) == (1, 0, ())
    assert topo.accelerator_type == "v5p-8"  # local 4 chips, not the pinned 16
    assert topo.host_bounds_str() == "1,1,1"


def test_config_rejects_duplicate_worker_hostnames():
    with pytest.raises(ValueError):
        Config(worker_hostnames=("a", "a", "b"))


def test_status_reports_overlaid_identity(tmp_path, capsys):
    """`status` must show the identity the daemon actually emits."""
    import json as jsonlib

    from kata_xpu_device_plugin_tpu.__main__ import main

    fake = _v5p_host(str(tmp_path / "host"))
    md = str(tmp_path / "md")
    _write_metadata(md, "vp-w-0,vp-w-1", 1)
    rc = main([
        "status", "--json",
        "--sysfs-root", fake.sysfs, "--dev-root", fake.dev,
        "--cdi-dir", str(tmp_path / "cdi"), "--metadata-dir", md,
        "--state-dir", "", "--metrics-port", "0", "--libtpu-host-path", "",
    ])
    assert rc == 0
    report = jsonlib.loads(capsys.readouterr().out)
    assert report["tpu"]["worker_id"] == 1
    assert report["tpu"]["worker_hostnames"] == ["vp-w-0", "vp-w-1"]
    assert report["tpu"]["num_hosts"] == 2


def test_persisted_peers_require_id_corroboration(tmp_path):
    """A reused node where GKE still sets a bare TPU_WORKER_ID must not
    resurrect a deleted slice's peer list unless the ids agree."""
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, ",".join(HOSTS4), 1)
    resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    import shutil

    shutil.rmtree(md)
    # Different id -> no merge, hostname-less membership stands.
    mem = resolve_membership({"TPU_WORKER_ID": "0"}, hostname="x", state_dir=state)
    assert mem is not None and (mem.worker_id, mem.hostnames) == (0, ())
    # Matching id -> persisted peers corroborate and merge.
    mem = resolve_membership({"TPU_WORKER_ID": "1"}, hostname="x", state_dir=state)
    assert mem is not None and (mem.worker_id, mem.hostnames) == (1, HOSTS4)


def test_persisted_peers_respect_num_hosts_hint(tmp_path):
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, ",".join(HOSTS4), 1)
    resolve_membership({}, hostname="x", metadata_dir=md, state_dir=state)
    import shutil

    shutil.rmtree(md)
    mem = resolve_membership(
        {"TPU_WORKER_ID": "1"}, hostname="x", state_dir=state, num_hosts_hint=1
    )
    assert mem is not None and mem.hostnames == ()
    assert load_state(state) is None  # stale state cleared


def test_merge_rejects_unaddressable_worker_id(tmp_path):
    md = str(tmp_path / "md")
    _write_metadata(md, "a,b")  # 2 peers, no worker-number
    mem = resolve_membership(
        {}, hostname="zz", explicit_worker_id=5, metadata_dir=md
    )
    assert mem is not None and (mem.worker_id, mem.hostnames) == (5, ())


def test_status_never_writes_state(tmp_path, capsys):
    from kata_xpu_device_plugin_tpu.__main__ import main

    fake = _v5p_host(str(tmp_path / "host"))
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, "vp-w-0,vp-w-1", 1)
    rc = main([
        "status", "--json",
        "--sysfs-root", fake.sysfs, "--dev-root", fake.dev,
        "--cdi-dir", str(tmp_path / "cdi"), "--metadata-dir", md,
        "--state-dir", state, "--metrics-port", "0", "--libtpu-host-path", "",
    ])
    assert rc == 0
    capsys.readouterr()
    assert load_state(state) is None  # read-only: nothing persisted


def test_refused_membership_is_never_persisted(tmp_path):
    """An identity the manager refuses (partial host × claimed multi-host)
    must not be written to — and must be purged from — the state file."""
    fake = FakeSysfsBuilder(root=str(tmp_path / "host"))
    for i in range(4):  # half of an 8-chip v5e machine
        fake.add_accel_chip(i)
        fake.add_pci_function(f"0000:0{i}:04.0", "1ae0", "0063", numa_node=0)
    md, state = str(tmp_path / "md"), str(tmp_path / "state")
    _write_metadata(md, "e-w-0,e-w-1", 1)
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        metadata_dir=md,
        state_dir=state,
        metrics_port=0,
        libtpu_host_path="",
    )
    mgr = PluginManager(cfg)
    tpu_inv, _ = mgr.scan()
    assert tpu_inv.topology.num_hosts == 1  # refused, failed closed
    assert load_state(state) is None  # nothing persisted, nothing to haunt


def test_scan_tpus_preserves_env_hostnames_without_id(tmp_path):
    """Direct scan_tpus callers still see the peer list even when no worker
    id is derivable from env (pod hostname not in the list)."""
    from kata_xpu_device_plugin_tpu.discovery import scan_tpus

    fake = _v5p_host(str(tmp_path / "host"))
    inv = scan_tpus(
        fake.sysfs, fake.dev, env={"TPU_WORKER_HOSTNAMES": "a,b,c,d"}
    )
    assert inv.topology.worker_id == 0
    assert inv.topology.worker_hostnames == ("a", "b", "c", "d")


def test_daemonset_mounts_state_dir():
    import yaml

    with open(os.path.join(os.path.dirname(__file__), "..", "deploy",
                           "kata-tpu-device-plugin.yaml")) as f:
        ds = next(d for d in yaml.safe_load_all(f) if d.get("kind") == "DaemonSet")
    spec = ds["spec"]["template"]["spec"]
    vols = {v["name"]: v for v in spec["volumes"]}
    mounts = {m["name"]: m for m in spec["containers"][0]["volumeMounts"]}
    assert vols["state"]["hostPath"]["path"] == "/var/run/kata-tpu"
    assert mounts["state"]["mountPath"] == "/var/run/kata-tpu"


def test_multislice_flags_emit_megascale_env(tmp_path):
    fake = _v5p_host(str(tmp_path / "host"))
    cfg = Config(
        sysfs_root=fake.sysfs,
        dev_root=fake.dev,
        cdi_dir=str(tmp_path / "cdi"),
        accelerator_type="v5p-8",
        num_slices=2,
        slice_id=1,
        megascale_coordinator="coord.svc:8080",
        state_dir="",
        metrics_port=0,
        libtpu_host_path="",
    )
    mgr = PluginManager(cfg)
    tpu_inv, _ = mgr.scan()
    env = _env_dict(build_tpu_spec(tpu_inv, cfg))
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_SLICE_ID"] == "1"
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "coord.svc:8080"


class TestGuestDistributed:
    """Guest-side jax.distributed bridge: the env the plugin injects must
    resolve to a consistent process group on every worker."""

    def test_single_host_noop(self):
        from kata_xpu_device_plugin_tpu.guest.distributed import (
            initialize_from_env,
            resolve,
        )

        cfg = resolve({})
        assert not cfg.multi_host and cfg.coordinator_address is None
        s = initialize_from_env({"TPU_WORKER_HOSTNAMES": "solo"},)
        assert s == {
            "multi_host": False, "num_processes": 1, "process_id": 0,
            "coordinator_address": None, "initialized": False,
        }

    def test_multi_host_consistent_across_workers(self):
        from kata_xpu_device_plugin_tpu.guest.distributed import resolve

        hosts = "tpu-w0,tpu-w1,tpu-w2,tpu-w3"
        cfgs = [
            resolve({"TPU_WORKER_HOSTNAMES": hosts, "TPU_WORKER_ID": str(i)})
            for i in range(4)
        ]
        # Every worker derives the SAME coordinator and group size, and its
        # own distinct process id — no extra coordination channel needed.
        assert {c.coordinator_address for c in cfgs} == {"tpu-w0:8476"}
        assert {c.num_processes for c in cfgs} == {4}
        assert [c.process_id for c in cfgs] == [0, 1, 2, 3]

    def test_dry_run_reports_without_jax(self):
        from kata_xpu_device_plugin_tpu.guest.distributed import initialize_from_env

        s = initialize_from_env(
            {"TPU_WORKER_HOSTNAMES": "a,b", "TPU_WORKER_ID": "1"}, dry_run=True
        )
        assert s["multi_host"] and s["coordinator_address"] == "a:8476"
        assert s["process_id"] == 1 and not s["initialized"]

    def test_contradictory_env_fails_closed(self):
        import pytest as _pytest

        from kata_xpu_device_plugin_tpu.guest.distributed import resolve

        with _pytest.raises(ValueError, match="TPU_WORKER_ID"):
            resolve({"TPU_WORKER_HOSTNAMES": "a,b"})
        with _pytest.raises(ValueError, match="out of range"):
            resolve({"TPU_WORKER_HOSTNAMES": "a,b", "TPU_WORKER_ID": "5"})
