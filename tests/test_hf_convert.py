"""Logit-parity of converted HF checkpoints vs the canonical ``transformers``
CPU implementations.

These are the strongest correctness oracles in the suite: every other model
test compares this framework against itself; here the reference is the
upstream modeling code each family's released checkpoints actually run on.
A convention drift anywhere — RoPE rotation, RMSNorm (1+w) offset, Gemma-2
post-norms/softcaps/window parity, GQA grouping, MoE routing — shows up as
a logit mismatch. Tiny random-init models (transformers + torch-cpu are in
the image; no weights are downloaded).
"""
from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp
from dataclasses import replace

from kata_xpu_device_plugin_tpu.models import forward
from kata_xpu_device_plugin_tpu.models.convert import (
    config_from_hf,
    from_hf,
    hf_config_dict,
    load_hf_checkpoint,
    save_hf_checkpoint,
    to_hf_state_dict,
)

B, S = 2, 32


def _hf_logits(model, tokens):
    model.eval()
    with torch.no_grad():
        out = model(
            input_ids=torch.from_numpy(tokens).long(),
            position_ids=torch.arange(tokens.shape[1])[None].expand(
                tokens.shape[0], -1
            ),
        )
    return out.logits.float().numpy()


def _ours_logits(hf_model, tokens, **cfg_overrides):
    params, cfg = from_hf(hf_model)
    cfg = replace(cfg, dtype=jnp.float32, **cfg_overrides)
    logits = forward(params, jnp.asarray(tokens), cfg)
    return np.asarray(logits, dtype=np.float32), cfg


def _assert_close(ours, hf):
    # Both fp32, different op orders; logits are O(1-10) at random init.
    np.testing.assert_allclose(ours, hf, rtol=2e-3, atol=2e-3)


def _tokens(vocab, seed=0):
    return np.random.RandomState(seed).randint(0, vocab, size=(B, S))


def test_llama_parity():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=500000.0, attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg)
    toks = _tokens(128)
    ours, cfg = _ours_logits(model, toks)
    assert cfg.activation == "swiglu" and not cfg.scale_embeddings
    _assert_close(ours, _hf_logits(model, toks))


def test_gemma_parity():
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = transformers.GemmaForCausalLM(hf_cfg)
    toks = _tokens(128, seed=1)
    ours, cfg = _ours_logits(model, toks)
    assert cfg.scale_embeddings and cfg.tie_embeddings
    _assert_close(ours, _hf_logits(model, toks))


def test_gemma2_parity():
    # Window small enough to bite at S=32 on the even (local) layers, and
    # both softcaps live — the full Gemma-2 block against upstream.
    hf_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, query_pre_attn_scalar=16, sliding_window=8,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    model = transformers.Gemma2ForCausalLM(hf_cfg)
    toks = _tokens(128, seed=2)
    ours, cfg = _ours_logits(model, toks)
    assert cfg.post_norms and cfg.attn_windows == (8, 0)
    assert cfg.attn_logits_softcap == 50.0 and cfg.logits_softcap == 30.0
    _assert_close(ours, _hf_logits(model, toks))


def test_llama31_rope_scaling_parity():
    """Llama-3.1-style rope_scaling (the long-context checkpoints' config)
    against HF's _compute_llama3_parameters. rope_theta=100 and
    original_max_position_embeddings=16 put this head_dim's wavelengths in
    ALL THREE bands (kept / smoothed / divided-by-factor), so a band-logic
    error cannot hide; S=32 > old_len so scaled positions are exercised."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=100.0, max_position_embeddings=64,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 2.0, "original_max_position_embeddings": 16,
        },
        attn_implementation="eager",
    )
    torch.manual_seed(13)
    model = transformers.LlamaForCausalLM(hf_cfg)
    toks = _tokens(128, seed=13)
    ours, cfg = _ours_logits(model, toks)
    assert cfg.rope_llama3_scaling == (8.0, 1.0, 2.0, 16.0)
    _assert_close(ours, _hf_logits(model, toks))
    # non-llama3 scaling types still fail closed
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf({**_DICT_BASE, "rope_scaling": {
            "rope_type": "yarn", "factor": 4.0}})
    # and the export direction round-trips the scaling dict
    p, c = from_hf(model)
    _, hf_dict = to_hf_state_dict(p, c, "llama")
    assert hf_dict["rope_scaling"]["rope_type"] == "llama3"
    assert hf_dict["rope_scaling"]["factor"] == 8.0


def test_mistral_sliding_window_parity():
    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, sliding_window=8, attn_implementation="eager",
    )
    torch.manual_seed(3)
    model = transformers.MistralForCausalLM(hf_cfg)
    toks = _tokens(128, seed=3)
    ours, cfg = _ours_logits(model, toks)
    assert cfg.sliding_window == 8
    _assert_close(ours, _hf_logits(model, toks))


def test_gemma3_parity():
    """Gemma-3 text: per-head QK-norms, a truncated 5:1 local/global
    layer pattern, DUAL rope (local base freq on windowed layers, global
    theta with a linear rescale on full layers), post-norms, no softcaps.
    n_layers=7 with pattern period 3 forces the truncated-tail path
    (minimal period = full depth) and a window small enough to bite."""
    hf_cfg = transformers.Gemma3TextConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=7, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, query_pre_attn_scalar=16, sliding_window=8,
        sliding_window_pattern=3, rope_theta=100_000.0,
        rope_local_base_freq=1000.0,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
        attn_implementation="eager",
    )
    torch.manual_seed(17)
    model = transformers.Gemma3ForCausalLM(hf_cfg)
    toks = _tokens(128, seed=17)
    ours, cfg = _ours_logits(model, toks)
    assert cfg.qk_norm and cfg.post_norms
    assert len(cfg.attn_windows) == len(cfg.rope_theta_cycle)
    assert 0 in cfg.attn_windows and 8 in cfg.attn_windows
    assert 1000.0 in cfg.rope_theta_cycle and 100_000.0 in cfg.rope_theta_cycle
    assert 4.0 in cfg.rope_linear_cycle
    _assert_close(ours, _hf_logits(model, toks))
    # import-only: export fails closed rather than dropping the dual rope
    params, _ = from_hf(model)
    with pytest.raises(ValueError, match="import-only"):
        to_hf_state_dict(params, cfg, "gemma3_text")
    with pytest.raises(ValueError, match="QK-norm|rope cycles"):
        to_hf_state_dict(params, cfg, "gemma2")


def test_qwen2_parity():
    """Qwen2: llama-style blocks plus additive q/k/v projection biases —
    torch random-inits the biases nonzero, so the bias path is genuinely
    exercised, GQA included."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        attn_implementation="eager",
    )
    torch.manual_seed(14)
    model = transformers.Qwen2ForCausalLM(hf_cfg)
    toks = _tokens(128, seed=14)
    ours, cfg = _ours_logits(model, toks)
    assert cfg.qkv_bias and cfg.activation == "swiglu"
    _assert_close(ours, _hf_logits(model, toks))
    # layer-gated windows fail closed rather than attending differently
    with pytest.raises(ValueError, match="use_sliding_window"):
        config_from_hf({**_DICT_BASE, "model_type": "qwen2",
                        "use_sliding_window": True, "sliding_window": 8})


def test_qwen2_export_roundtrip(tmp_path):
    """Export with biases loads back into transformers with the same
    logits; bias-bearing trees refuse to export as bias-free families."""
    from kata_xpu_device_plugin_tpu.models import init_params
    import jax

    cfg = replace(
        config_from_hf({"model_type": "qwen2", "vocab_size": 128,
                        "hidden_size": 64, "intermediate_size": 128,
                        "num_hidden_layers": 2, "num_attention_heads": 4,
                        "num_key_value_heads": 2}),
        dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(15), cfg)
    # init biases are zeros — randomize so the export carries real values
    layers = dict(params["layers"])
    for i, b in enumerate(("bq", "bk", "bv")):
        layers[b] = jax.random.normal(
            jax.random.PRNGKey(100 + i), layers[b].shape
        ) * 0.1
    params = {**params, "layers": layers}
    save_hf_checkpoint(params, cfg, "qwen2", str(tmp_path / "out"))
    model = transformers.AutoModelForCausalLM.from_pretrained(
        str(tmp_path / "out"), attn_implementation="eager"
    )
    toks = _tokens(128, seed=15)
    ours = np.asarray(forward(params, jnp.asarray(toks), cfg), np.float32)
    _assert_close(ours, _hf_logits(model, toks))
    with pytest.raises(ValueError, match="qkv_bias"):
        to_hf_state_dict(params, cfg, "llama")


def test_qwen2_fused_quantized_serving():
    """The capstone journey for the bias-carrying family: converted Qwen2
    through fuse (bq/bk/bv → one bqkv) → bf16 serving token-identical to
    generate() → int8 serving runs (biases pass through quantization)."""
    from kata_xpu_device_plugin_tpu.guest.serving import serve_batch
    from kata_xpu_device_plugin_tpu.models import generate
    from kata_xpu_device_plugin_tpu.models.transformer import (
        fuse_decoder_params,
    )
    from kata_xpu_device_plugin_tpu.ops.quant import quantize_decoder_params

    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        attn_implementation="eager",
    )
    torch.manual_seed(16)
    params, cfg = from_hf(transformers.Qwen2ForCausalLM(hf_cfg))
    cfg = replace(cfg, dtype=jnp.float32)
    prompt = np.asarray(_tokens(128, seed=16)[0, :12])
    steps = 8
    ref = np.asarray(
        generate(params, jnp.asarray(prompt)[None], cfg, steps=steps)
    )[0]
    fused = fuse_decoder_params(params)
    assert "bqkv" in fused["layers"] and "bq" not in fused["layers"]
    out = serve_batch(fused, cfg, [prompt], steps, max_batch=2, max_len=32)[0]
    np.testing.assert_array_equal(np.asarray(out), ref)
    q = quantize_decoder_params(fused)
    qout = serve_batch(q, cfg, [prompt], steps, max_batch=2, max_len=32)[0]
    assert len(qout) == steps


def test_mixtral_sliding_window_mapped():
    """Mixtral carries mistral's sliding_window; it must convert, not drop
    (a window-bearing fine-tune attends differently past the window)."""
    cfg = config_from_hf({
        "model_type": "mixtral", "vocab_size": 128, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "num_local_experts": 4, "num_experts_per_tok": 2,
        "sliding_window": 4096,
    })
    assert cfg.sliding_window == 4096 and cfg.moe_num_experts == 4


def test_mixtral_moe_parity():
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, num_local_experts=4, num_experts_per_tok=2,
        attn_implementation="eager", router_jitter_noise=0.0,
    )
    torch.manual_seed(4)
    model = transformers.MixtralForCausalLM(hf_cfg)
    toks = _tokens(128, seed=4)
    # HF routes with no capacity limit; raise ours so nothing drops and
    # the comparison is routing-for-routing.
    ours, cfg = _ours_logits(model, toks, moe_capacity_factor=4.0)
    assert cfg.moe_num_experts == 4 and cfg.moe_top_k == 2
    _assert_close(ours, _hf_logits(model, toks))


def test_decode_cache_path_matches_hf_forward():
    """Teacher-forced decode parity: drive OUR prefill→stepwise KV-cache
    decode on a fixed token stream and compare each step's logits to the
    HF full-sequence forward at that position. This extends the parity
    oracle from one forward to the incremental cache machinery (cache
    writes, q_offset masking, position handling) without the argmax
    tie-break flakiness greedy-vs-greedy would have on random weights."""
    from kata_xpu_device_plugin_tpu.models.transformer import init_kv_caches

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, attn_implementation="eager",
    )
    torch.manual_seed(6)
    model = transformers.LlamaForCausalLM(hf_cfg)
    params, cfg = from_hf(model)
    cfg = replace(cfg, dtype=jnp.float32)

    steps, prompt_len = 8, S - 8
    toks = _tokens(128, seed=6)  # the full fixed stream, [B, S]
    hf = _hf_logits(model, toks)  # [B, S, V] — the per-position oracle

    prompt = jnp.asarray(toks[:, :prompt_len])
    caches = init_kv_caches(cfg, B, S)
    positions = jnp.arange(prompt_len)[None, :].repeat(B, 0)
    logits_p, caches = forward(
        params, prompt, cfg, positions=positions, kv_caches=caches,
        cache_offset=jnp.int32(0), prefill=True,
    )
    np.testing.assert_allclose(
        np.asarray(logits_p, np.float32), hf[:, :prompt_len], rtol=2e-3,
        atol=2e-3,
    )
    for t in range(steps):
        pos = prompt_len + t
        tok = jnp.asarray(toks[:, pos:pos + 1])
        logits_t, caches = forward(
            params, tok, cfg,
            positions=jnp.full((B, 1), pos, jnp.int32),
            kv_caches=caches, cache_offset=jnp.int32(pos),
        )
        np.testing.assert_allclose(
            np.asarray(logits_t[:, 0], np.float32), hf[:, pos],
            rtol=2e-3, atol=2e-3, err_msg=f"step {t} (position {pos})",
        )


def test_export_roundtrip_into_transformers():
    """The reverse direction: a tree exported with to_hf_state_dict loads
    into a fresh transformers model (strict=False, but with explicit
    assertions: nothing unexpected, and the only permitted misses are
    derived buffers — rotary tables — and the tied lm_head) and produces
    the same logits our forward does — weights trained here flow back to
    the HF ecosystem. Exercised on the two families with the most
    convention deltas (llama: norm offset re-added; gemma2: post-norm
    fan-out)."""
    from kata_xpu_device_plugin_tpu.models import init_params

    for model_type, hf_cfg in (
        ("llama", transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16,
            attn_implementation="eager")),
        ("gemma2", transformers.Gemma2Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, query_pre_attn_scalar=16,
            sliding_window=8, attn_implementation="eager")),
    ):
        cfg = replace(config_from_hf(hf_cfg), dtype=jnp.float32)
        params = init_params(__import__("jax").random.PRNGKey(8), cfg)
        sd, _ = to_hf_state_dict(params, cfg, model_type)
        model = (transformers.LlamaForCausalLM if model_type == "llama"
                 else transformers.Gemma2ForCausalLM)(hf_cfg)
        missing, unexpected = model.load_state_dict(
            {k: torch.from_numpy(v) for k, v in sd.items()}, strict=False
        )
        # tied lm_head / rotary buffers may be absent from the export;
        # nothing we exported may be unexpected.
        assert not unexpected, unexpected
        assert all("rotary" in m or "lm_head" in m for m in missing), missing
        toks = _tokens(128, seed=8)
        ours = np.asarray(
            forward(params, jnp.asarray(toks), cfg), np.float32
        )
        _assert_close(ours, _hf_logits(model, toks))


def test_save_hf_checkpoint_roundtrip(tmp_path):
    """save_hf_checkpoint → load_hf_checkpoint is the identity (config and
    tree), and the directory is transformers-loadable."""
    from kata_xpu_device_plugin_tpu.models import init_params
    import jax

    cfg = replace(
        config_from_hf({"model_type": "mistral", "vocab_size": 128,
                        "hidden_size": 64, "intermediate_size": 128,
                        "num_hidden_layers": 2, "num_attention_heads": 4,
                        "num_key_value_heads": 2, "head_dim": 16,
                        "sliding_window": 8}),
        dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(9), cfg)
    save_hf_checkpoint(params, cfg, "mistral", str(tmp_path / "out"))
    params2, cfg2 = load_hf_checkpoint(str(tmp_path / "out"))
    assert replace(cfg2, dtype=jnp.float32) == cfg
    flat = {str(k): v for k, v in jax.tree_util.tree_leaves_with_path(params)}
    back = {str(k): v for k, v in jax.tree_util.tree_leaves_with_path(params2)}
    assert flat.keys() == back.keys()
    for k in flat:
        np.testing.assert_allclose(
            np.asarray(flat[k]), np.asarray(back[k]), atol=1e-7, err_msg=k
        )
    model = transformers.AutoModelForCausalLM.from_pretrained(
        str(tmp_path / "out"), attn_implementation="eager"
    )
    toks = _tokens(128, seed=9)
    ours = np.asarray(forward(params, jnp.asarray(toks), cfg), np.float32)
    _assert_close(ours, _hf_logits(model, toks))


def test_export_refuses_unexpressible_configs():
    """hf_config_dict fails closed rather than dropping semantics."""
    from kata_xpu_device_plugin_tpu.models import gemma2_test_config, llama3_train_test

    with pytest.raises(ValueError, match="activation|softcap|post"):
        hf_config_dict(gemma2_test_config(), "llama")
    with pytest.raises(ValueError, match="activation|attn_windows|post_norms"):
        hf_config_dict(llama3_train_test(), "gemma2")
    with pytest.raises(ValueError, match="activation|scale_embeddings"):
        hf_config_dict(llama3_train_test(), "gemma")
    # mistral expresses ONE uniform window — a per-layer cycle must not
    # export to silently different attention
    with pytest.raises(ValueError, match="attn_windows"):
        hf_config_dict(
            replace(llama3_train_test(), attn_windows=(8, 0)), "mistral"
        )


def test_converted_checkpoint_through_the_serving_stack():
    """The capstone journey a switching user actually takes: HF checkpoint
    → convert → fuse → int8-quantize → continuous-batching server — and
    the quantized serving output matches plain bf16 greedy generate on the
    SAME converted weights token-for-token... is too strong a claim for
    int8 (quantization legitimately flips near-ties on random weights), so
    the locked property is: the full pipeline runs, and the bf16 serving
    path is token-identical to generate() on the converted tree."""
    from kata_xpu_device_plugin_tpu.guest.serving import serve_batch
    from kata_xpu_device_plugin_tpu.models import generate
    from kata_xpu_device_plugin_tpu.models.transformer import (
        fuse_decoder_params,
    )
    from kata_xpu_device_plugin_tpu.ops.quant import quantize_decoder_params

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, attn_implementation="eager",
    )
    torch.manual_seed(10)
    model = transformers.LlamaForCausalLM(hf_cfg)
    params, cfg = from_hf(model, dtype=jnp.bfloat16)

    prompt = np.asarray(_tokens(128, seed=10)[0, :12])
    steps = 8
    ref = np.asarray(
        generate(params, jnp.asarray(prompt)[None], cfg, steps=steps)
    )[0]

    fused = fuse_decoder_params(params)
    out = serve_batch(fused, cfg, [prompt], steps, max_batch=2, max_len=32)[0]
    np.testing.assert_array_equal(np.asarray(out), ref)

    q = quantize_decoder_params(fused)
    qout = serve_batch(q, cfg, [prompt], steps, max_batch=2, max_len=32)[0]
    assert len(qout) == steps  # int8 path runs end-to-end on converted tree


def test_converted_draft_model_speculative_decoding():
    """Two independently converted HF checkpoints compose as speculative
    target + draft (shared vocab, different depths/widths allowed) and the
    output is token-identical to plain greedy on the target — the draft
    moves only the acceptance rate, never the tokens."""
    from kata_xpu_device_plugin_tpu.models import generate
    from kata_xpu_device_plugin_tpu.models.speculative import (
        generate_speculative,
    )

    def mk(layers, hidden, seed):
        hf_cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=hidden, intermediate_size=2 * hidden,
            num_hidden_layers=layers, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, attn_implementation="eager",
        )
        torch.manual_seed(seed)
        p, c = from_hf(transformers.LlamaForCausalLM(hf_cfg))
        return p, replace(c, dtype=jnp.float32)

    target_p, target_c = mk(3, 64, 11)
    draft_p, draft_c = mk(1, 64, 12)  # shallower independent draft

    prompt = jnp.asarray(_tokens(128, seed=11)[:1, :12])
    steps = 8
    ref = np.asarray(generate(target_p, prompt, target_c, steps=steps))
    out = generate_speculative(
        target_p, prompt, target_c, steps=steps, k=3,
        draft=(draft_p, draft_c),
    )
    np.testing.assert_array_equal(np.asarray(out)[:, :steps], ref)


def test_unsupported_family_rejected():
    with pytest.raises(ValueError, match="unsupported model_type"):
        config_from_hf({"model_type": "gpt2"})


_DICT_BASE = dict(
    model_type="llama", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16,
)


def test_unsupported_conventions_fail_closed():
    """A checkpoint must never convert cleanly into wrong logits: scaled
    RoPE (Llama-3.1 style) and projection biases are rejected, not
    silently dropped."""
    # llama3 scaling is SUPPORTED, but a malformed dict must raise a clear
    # ValueError, not a KeyError deep in the field access
    with pytest.raises(ValueError, match="needs numeric"):
        config_from_hf({**_DICT_BASE, "rope_scaling": {
            "rope_type": "llama3", "factor": 8.0}})
    with pytest.raises(ValueError, match="needs numeric"):
        config_from_hf({**_DICT_BASE, "rope_scaling": {
            "rope_type": "llama3", "factor": None, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192}})
    # the no-op "default" rope_type (serialized by some configs) is fine
    config_from_hf({**_DICT_BASE, "rope_scaling": {"rope_type": "default"}})
    with pytest.raises(ValueError, match="attention_bias"):
        config_from_hf({**_DICT_BASE, "attention_bias": True})
    with pytest.raises(ValueError, match="mlp_bias"):
        config_from_hf({**_DICT_BASE, "mlp_bias": True})
    # a non-default MLP activation must not silently become silu/gelu-tanh
    with pytest.raises(ValueError, match="hidden_act"):
        config_from_hf({**_DICT_BASE, "hidden_act": "gelu"})
    with pytest.raises(ValueError, match="hidden_activation"):
        config_from_hf({**_DICT_BASE, "model_type": "gemma",
                        "hidden_activation": "gelu"})


def test_gemma_head_dim_defaults_to_class_default():
    """save_pretrained omits head_dim when it equals the Gemma class
    default 256 — and d_model // n_heads is NOT 256 for the released
    gemma-7b/gemma2-9b/gemma3-4b geometries, so the quotient fallback
    mis-derives every projection shape. Absent head_dim on a gemma family
    must mean 256; the llama families keep the quotient derivation."""
    gemma7b_ish = dict(
        model_type="gemma", vocab_size=256, hidden_size=3072,
        intermediate_size=512, num_hidden_layers=2,
        num_attention_heads=16, num_key_value_heads=16,
    )
    assert config_from_hf(gemma7b_ish).head_dim == 256  # not 3072//16=192
    llama = dict(_DICT_BASE)
    llama.pop("head_dim")
    assert config_from_hf(llama).head_dim == 64 // 4


def test_mismatched_q_proj_shape_fails_at_convert_time():
    """A config whose head_dim disagrees with the checkpoint weights must
    raise a descriptive convert-time error, not a reshape crash at first
    forward."""
    from kata_xpu_device_plugin_tpu.models.convert import params_from_hf

    cfg = config_from_hf(_DICT_BASE)
    # state_dict built for head_dim=8 (q_dim 32) vs the config's 16 (64).
    wrong = {}
    for i in range(cfg.n_layers):
        L = f"model.layers.{i}."
        wrong[L + "self_attn.q_proj.weight"] = np.zeros((32, 64), np.float32)
        wrong[L + "self_attn.k_proj.weight"] = np.zeros((16, 64), np.float32)
    wrong["model.embed_tokens.weight"] = np.zeros((128, 64), np.float32)
    with pytest.raises(ValueError, match="q_proj weight is .* head_dim"):
        params_from_hf(wrong, cfg, "llama")


def test_export_stamps_max_position_embeddings():
    """Unscaled llama/mistral/qwen2 exports accept an explicit trained
    context length; without it the key is absent (HF class default 2048
    would cap serving) and the llama3-scaled derivation still applies."""
    from kata_xpu_device_plugin_tpu.models.transformer import (
        init_params,
        tiny_test_config,
    )

    cfg = tiny_test_config(
        activation="swiglu", scale_embeddings=False, tie_embeddings=False
    )
    out = hf_config_dict(cfg, "llama", max_position_embeddings=8192)
    assert out["max_position_embeddings"] == 8192
    assert "max_position_embeddings" not in hf_config_dict(cfg, "llama")

    # threads through the state-dict export entry point too
    import jax

    params = init_params(jax.random.PRNGKey(0), cfg)
    _, hf_cfg = to_hf_state_dict(
        params, cfg, "llama", max_position_embeddings=4096
    )
    assert hf_cfg["max_position_embeddings"] == 4096

    # explicit value overrides the llama3-scaled factor×original derivation
    scaled = replace(cfg, rope_llama3_scaling=(8.0, 1.0, 4.0, 8192.0))
    derived = hf_config_dict(scaled, "llama")
    assert derived["max_position_embeddings"] == 8 * 8192
    overridden = hf_config_dict(scaled, "llama", max_position_embeddings=131072)
    assert overridden["max_position_embeddings"] == 131072


def test_dict_config_uses_family_tie_default():
    """save_pretrained omits fields equal to the class default, so a raw
    gemma config.json usually has NO tie_word_embeddings key — the family
    default (tied) must apply, not a blanket False."""
    gemma = dict(_DICT_BASE, model_type="gemma")
    gemma.pop("head_dim")
    assert config_from_hf(gemma).tie_embeddings is True
    assert config_from_hf(_DICT_BASE).tie_embeddings is False


def test_load_hf_checkpoint_dir_sharded(tmp_path):
    """save_pretrained round trip, forced into MULTIPLE safetensors shards
    with an index — the on-disk layout real checkpoints ship in. The loaded
    tree must match the in-memory conversion exactly; a raw torch-pickle
    checkpoint dir is rejected."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16,
    )
    torch.manual_seed(7)
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.save_pretrained(tmp_path / "ckpt", max_shard_size="100KB")
    import os
    assert os.path.exists(tmp_path / "ckpt" / "model.safetensors.index.json")

    params, cfg = load_hf_checkpoint(str(tmp_path / "ckpt"))
    ref_params, ref_cfg = from_hf(model)
    assert cfg == ref_cfg
    import jax
    flat = {str(k): v for k, v in jax.tree_util.tree_leaves_with_path(params)}
    ref = {str(k): v for k, v in jax.tree_util.tree_leaves_with_path(ref_params)}
    assert flat.keys() == ref.keys()
    for k in flat:
        np.testing.assert_allclose(
            np.asarray(flat[k]), np.asarray(ref[k]), err_msg=k
        )

    # config.json present but no safetensors → the explicit rejection
    # (covers the pytorch_model.bin-only layout).
    bare = tmp_path / "bin_only"
    bare.mkdir()
    (bare / "config.json").write_text(
        (tmp_path / "ckpt" / "config.json").read_text()
    )
    with pytest.raises(FileNotFoundError, match="safetensors"):
        load_hf_checkpoint(str(bare))


def test_bfloat16_target_dtype():
    """Conversion straight to bf16 (the deployment dtype) — exercises the
    per-layer dtype cast path that keeps peak host memory bounded."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, attn_implementation="eager",
    )
    torch.manual_seed(5)
    model = transformers.LlamaForCausalLM(hf_cfg)
    params, cfg = from_hf(model, dtype=jnp.bfloat16)
    assert params["layers"]["wq"].dtype == jnp.bfloat16
    toks = _tokens(128, seed=5)
    ours = np.asarray(forward(params, jnp.asarray(toks), cfg), np.float32)
    # bf16 weights vs the fp32 HF forward: loose tolerance, same argmax
    # almost everywhere is the meaningful check at this precision.
    hf = _hf_logits(model, toks)
    agree = (ours.argmax(-1) == hf.argmax(-1)).mean()
    assert agree > 0.9, agree
    # and the export side preserves the tree's dtype (no fp32 doubling)
    sd, _ = to_hf_state_dict(params, cfg, "llama")
    assert sd["model.layers.0.self_attn.q_proj.weight"].dtype == jnp.bfloat16
    assert sd["model.layers.0.input_layernorm.weight"].dtype == jnp.bfloat16
