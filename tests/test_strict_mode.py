"""Runtime strict mode (ISSUE 4): ``compat.jaxapi.strict_mode`` /
``allow_transfer`` / ``KATA_TPU_STRICT`` — the runtime half of the
jaxguard contract.

Covers: the env gate; rank-promotion and debug-nans enforcement inside
the scope; the transfer guard catching an INJECTED implicit transfer in
the overlapped decode loop (the exact pre-PR3 host-round-trip
regression); the sanctioned DeviceFence/admission paths passing clean
with token-identical output; the guard-trip obs event; and the
warn-once no-op on JAX lines without ``transfer_guard``.
"""
import types
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.compat import jaxapi
from kata_xpu_device_plugin_tpu.guest import serving as serving_mod
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params

_HAS_GUARD = hasattr(jax, "transfer_guard")


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_test_config()
    params = init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _serve(params, cfg, n=4, **kw):
    srv = GenerationServer(params, cfg, max_batch=2, max_len=64, chunk=4, **kw)
    rids = [srv.submit(np.arange(1, 9, dtype=np.int32), 12) for _ in range(n)]
    return srv, rids, srv.run()


# ----- env gate --------------------------------------------------------------


def test_strict_enabled_env_parsing():
    assert not jaxapi.strict_enabled(env={})
    for truthy in ("1", "true", "YES", "on"):
        assert jaxapi.strict_enabled(env={"KATA_TPU_STRICT": truthy})
    for falsy in ("0", "", "no", "off"):
        assert not jaxapi.strict_enabled(env={"KATA_TPU_STRICT": falsy})


def test_server_reads_env_gate(tiny, monkeypatch):
    params, cfg = tiny
    monkeypatch.setenv("KATA_TPU_STRICT", "1")
    assert GenerationServer(params, cfg, max_batch=1, max_len=32).strict
    monkeypatch.delenv("KATA_TPU_STRICT")
    assert not GenerationServer(params, cfg, max_batch=1, max_len=32).strict
    # explicit param overrides the env either way
    monkeypatch.setenv("KATA_TPU_STRICT", "1")
    assert not GenerationServer(
        params, cfg, max_batch=1, max_len=32, strict=False
    ).strict


# ----- scope semantics -------------------------------------------------------


@pytest.mark.skipif(not _HAS_GUARD, reason="jax lacks transfer_guard")
def test_strict_mode_blocks_implicit_transfer_allows_explicit():
    f = jax.jit(lambda a: a * 2)
    x = jnp.arange(4.0)
    f(x)  # compile outside
    host = np.arange(4.0, dtype=np.float32)
    with jaxapi.strict_mode(rank_promotion=None):
        f(x)  # device inputs: clean
        f(jax.device_put(host))  # explicit upload: clean
        with pytest.raises(Exception, match="[Tt]ransfer"):
            f(host)  # implicit upload: trips
        with jaxapi.allow_transfer("sanctioned test read"):
            f(host)  # hatch re-allows


@pytest.mark.skipif(
    not hasattr(jax, "numpy_rank_promotion"), reason="no rank ctx"
)
def test_strict_mode_rank_promotion_raises():
    # Operands built OUTSIDE the scope: under the transfer guard, even a
    # jnp.zeros literal is an implicit upload (that strictness is the
    # point, but rank promotion is what THIS test pins).
    a, b = jnp.zeros((3,)), jnp.zeros((2, 3))
    with jaxapi.strict_mode():
        with pytest.raises(ValueError, match="rank_promotion"):
            a + b
    # outside the scope the default behavior is restored
    a + b


@pytest.mark.skipif(not hasattr(jax, "debug_nans"), reason="no debug_nans")
def test_strict_mode_debug_nans():
    neg = jnp.float32(-1.0)  # built outside the transfer guard
    with jaxapi.strict_mode(debug_nans=True):
        with pytest.raises(FloatingPointError):
            jnp.log(neg).block_until_ready()


def test_strict_mode_noop_warns_once_without_guard():
    fake_jax = types.SimpleNamespace(__version__="0.3.0")  # no transfer_guard
    jaxapi._strict_warned = False
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with jaxapi.strict_mode(jax_mod=fake_jax):
                pass
            with jaxapi.strict_mode(jax_mod=fake_jax):
                pass
        relevant = [w for w in caught if "transfer_guard" in str(w.message)]
        assert len(relevant) == 1  # warn-once, then silent no-op
    finally:
        jaxapi._strict_warned = False


def test_allow_transfer_is_safe_outside_strict():
    with jaxapi.allow_transfer("no active guard"):
        assert float(jnp.float32(3.0)) == 3.0


# ----- serving integration ---------------------------------------------------


@pytest.mark.skipif(not _HAS_GUARD, reason="jax lacks transfer_guard")
def test_strict_overlapped_serving_matches_lockstep(tiny):
    """The sanctioned paths — admission prefill reads and the DeviceFence
    retire — pass under the guard, and strict output is token-identical
    to the unguarded lock-step loop."""
    params, cfg = tiny
    _, rids_s, res_s = _serve(params, cfg, strict=True, overlap=True)
    _, rids_l, res_l = _serve(params, cfg, strict=False, overlap=False)
    for a, b in zip(rids_s, rids_l):
        assert np.array_equal(res_s[a], res_l[b])


@pytest.mark.skipif(not _HAS_GUARD, reason="jax lacks transfer_guard")
def test_strict_catches_injected_implicit_transfer(tiny, monkeypatch,
                                                   tmp_path):
    """Reintroduce the pre-pipelining host round-trip (decode fed from
    host numpy instead of on-device state): the guard must raise, and a
    strict/guard_trip event must land in the obs stream."""
    params, cfg = tiny
    real = serving_mod._serve_decode

    def leaky(params, caches, tok, pos, *args, **kw):
        return real(params, caches, np.asarray(tok), pos, *args, **kw)

    monkeypatch.setattr(serving_mod, "_serve_decode", leaky)
    sink = obs.EventSink(str(tmp_path / "events.jsonl"))
    old_sink = obs.default_sink()
    obs.set_default_sink(sink)
    try:
        srv = GenerationServer(
            params, cfg, max_batch=2, max_len=64, chunk=4, strict=True
        )
        srv.submit(np.arange(1, 9, dtype=np.int32), 12)
        with pytest.raises(Exception, match="[Tt]ransfer"):
            srv.run()
    finally:
        obs.set_default_sink(old_sink)
    trips = [
        e for e in obs.read_events(sink.path)
        if e.get("kind") == "strict" and e.get("name") == "guard_trip"
    ]
    assert trips and trips[0]["scope"] == "serving.decode_dispatch"
    # the unguarded server accepts the same injected transfer silently —
    # that silence is what strict mode exists to remove
    monkeypatch.setattr(serving_mod, "_serve_decode", real)
    srv2 = GenerationServer(
        params, cfg, max_batch=2, max_len=64, chunk=4, strict=False
    )
    srv2.submit(np.arange(1, 9, dtype=np.int32), 12)
    assert srv2.run()


@pytest.mark.skipif(not _HAS_GUARD, reason="jax lacks transfer_guard")
def test_strict_batched_admission_and_buckets(tiny):
    # The batched [N, bucket] admission prefill path also runs inside the
    # guard (under the allow_transfer hatch) — burst arrival must not trip.
    params, cfg = tiny
    srv = GenerationServer(
        params, cfg, max_batch=4, max_len=64, chunk=4, strict=True,
        prefill_buckets=(16,),
    )
    rids = [srv.submit(np.arange(1, 6 + i, dtype=np.int32), 8)
            for i in range(6)]
    res = srv.run()
    assert sorted(res) == sorted(rids)
    assert srv.stats()["prefill_batches"] >= 1
