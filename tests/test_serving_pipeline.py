"""Pipelined serving (ISSUE 3): overlapped decode dispatch, batched
admission prefill, and the persistent compilation cache.

Oracles:
- OVERLAP is a schedule, not a numerics change: the pipelined server's
  greedy output must be token-identical to the lock-step server's — and
  therefore to a lone ``generate()`` per request — under queue pressure,
  ragged budgets, and eos stops.
- BATCHED admission prefill equals N single-row prefills: same cache
  slices (to float tolerance), same logits rows, same served tokens.
- The PERSISTENT cache round-trips: a second trace of the same executable
  is served from the cache directory, writing no new entries.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer, serve_batch
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    generate,
    init_params,
    prefill,
    prefill_batch,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=1):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


def _oracle(params, cfg, prompt, steps, max_len):
    return np.asarray(
        generate(params, jnp.asarray(prompt)[None, :], cfg, steps,
                 max_len=max_len)
    )[0]


# ----- overlapped vs lock-step token identity ------------------------------


def test_overlap_matches_lockstep_and_oracle(model):
    # Queue pressure (6 requests / 2 slots), ragged budgets off chunk
    # boundaries: the pipelined schedule admits one round later than
    # lock-step but every request's tokens must be identical.
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3, 10, 5], seed=2)
    budgets = [8, 13, 7, 11, 8, 9]

    def run(overlap):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               chunk=4, overlap=overlap)
        rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
        res = srv.run()
        return [res[r] for r in rids]

    ref = run(overlap=False)
    out = run(overlap=True)
    for p, n, r, o in zip(prompts, budgets, ref, out):
        np.testing.assert_array_equal(o, r)
        np.testing.assert_array_equal(o, _oracle(params, cfg, p, n, 32))


def test_overlap_eos_stops_early(model):
    # eos fires mid-chunk while the NEXT chunk is already in flight: the
    # stale row's tokens must be discarded, the trimmed output identical.
    cfg, params = model
    (p,) = _prompts(cfg, [6], seed=4)
    ref = _oracle(params, cfg, p, 16, 32)
    eos = int(ref[3])
    stop = int(np.where(ref == eos)[0][0])
    out = serve_batch(params, cfg, [p], max_new_tokens=16, max_batch=2,
                      max_len=32, chunk=4, eos_id=eos, overlap=True)
    np.testing.assert_array_equal(out[0], ref[: stop + 1])


def test_overlap_dispatch_gate_skips_dead_chunks(model):
    # Budgets aligned to chunk boundaries: every in-flight request is
    # CERTAIN to finish at retire, so the pipeline must not dispatch the
    # provably-garbage next chunk — round counts match lock-step exactly.
    cfg, params = model
    prompts = _prompts(cfg, [5, 7], seed=6)

    def run(overlap):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               chunk=4, overlap=overlap)
        rids = [srv.submit(p, 9) for p in prompts]  # 1 prefill + 8 = 2 chunks
        res = srv.run()
        return [res[r] for r in rids], srv.stats()

    ref, st_lock = run(overlap=False)
    out, st_over = run(overlap=True)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    assert st_over["rounds"] == st_lock["rounds"]


def test_overlap_sampling_respects_budget_and_seed(model):
    cfg, params = model
    prompts = _prompts(cfg, [5, 7, 4], seed=5)

    def run(seed):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               chunk=4, temperature=0.9, top_k=8,
                               seed=seed, overlap=True)
        rids = [srv.submit(p, 9) for p in prompts]
        res = srv.run()
        return [res[r] for r in rids]

    a, b, c = run(42), run(42), run(43)
    assert all(len(x) == 9 and x.dtype == np.int32 for x in a)
    for x, y in zip(a, b):  # same seed → reproducible stream
        np.testing.assert_array_equal(x, y)
    assert not all(np.array_equal(x, y) for x, y in zip(a, c))


def test_overlap_submit_between_runs(model):
    # The pipeline must drain fully at run() exit; a second submit/run on
    # the same server starts from clean state and stays oracle-exact.
    cfg, params = model
    p1, p2 = _prompts(cfg, [5, 9], seed=7)
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4,
                           overlap=True)
    r1 = srv.submit(p1, 10)
    first = srv.run()
    r2 = srv.submit(p2, 7)
    second = srv.run()
    np.testing.assert_array_equal(first[r1], _oracle(params, cfg, p1, 10, 32))
    np.testing.assert_array_equal(second[r2], _oracle(params, cfg, p2, 7, 32))


# ----- batched admission prefill -------------------------------------------


def test_prefill_batch_matches_sequential_rows(model):
    # The [N, bucket] admission forward vs N single-row prefills: per-row
    # cache slices and last-token logits agree to float tolerance (rows
    # are independent math; batching changes layout, not values).
    cfg, params = model
    lengths = [6, 9, 4]
    pad = 12
    prompts = _prompts(cfg, lengths, seed=8)
    batch = np.zeros((len(prompts), pad), np.int32)
    for i, p in enumerate(prompts):
        batch[i, : len(p)] = p
    caches_b, logits_b, pos_b = prefill_batch(
        params, jnp.asarray(batch), cfg, 32,
        jnp.asarray(np.array(lengths, np.int32)),
    )
    np.testing.assert_array_equal(np.asarray(pos_b), lengths)
    for i, (p, n) in enumerate(zip(prompts, lengths)):
        caches_i, logits_i, pos_i = prefill(
            params, jnp.asarray(np.pad(p, (0, pad - n)))[None], cfg, 32,
            return_logits=True, true_len=jnp.int32(n),
        )
        assert int(pos_i) == n
        np.testing.assert_allclose(
            np.asarray(logits_b)[i], np.asarray(logits_i)[0], rtol=2e-5,
            atol=1e-5,
        )
        for cb, ci in zip(caches_b, caches_i):
            np.testing.assert_allclose(
                np.asarray(cb[:, i, :n]), np.asarray(ci[:, 0, :n]),
                rtol=2e-5, atol=1e-5,
            )


def test_batched_admission_used_and_token_identical(model):
    # Same-bucket burst through the server: the batched path must actually
    # engage (stats counter) and the served tokens must equal the
    # per-request generate() oracle — batching is admission mechanics,
    # never a numerics change.
    cfg, params = model
    prompts = _prompts(cfg, [3, 9, 5, 12], seed=9)
    srv = GenerationServer(params, cfg, max_batch=4, max_len=32,
                           prefill_buckets=(16,))
    rids = [srv.submit(p, 10) for p in prompts]
    res = srv.run()
    assert srv.stats()["prefill_batches"] >= 1
    for p, rid in zip(prompts, rids):
        np.testing.assert_array_equal(res[rid], _oracle(params, cfg, p, 10, 32))


def test_batched_admission_arena_matches_sequential(model):
    # After a batched admission, the arena's slot slices equal the ones N
    # sequential _fill_slot admissions write (same requests, same slots).
    cfg, params = model
    prompts = _prompts(cfg, [7, 5], seed=10)

    def admit(buckets):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               prefill_buckets=buckets)
        for p in prompts:
            srv.submit(p, 4)
        srv._admit()  # admission only — no decode round
        return srv

    batched = admit(buckets=(8,))
    sequential = admit(buckets=())  # distinct lengths → per-request path
    assert batched.stats()["prefill_batches"] == 1
    assert sequential.stats()["prefill_batches"] == 0
    for i, n in enumerate(len(p) for p in prompts):
        for cb, cs in zip(batched.arena, sequential.arena):
            np.testing.assert_allclose(
                np.asarray(cb[:, i, :n]), np.asarray(cs[:, i, :n]),
                rtol=2e-5, atol=1e-5,
            )


def test_admission_is_fifo_prefix_under_interleaved_buckets(model):
    # Interleaved bucket sizes with >= 3 free slots: the admitted SET must
    # still be the queue's FIFO prefix (no later request jumps one that
    # fits), even though grouping prefillls same-bucket requests together
    # within the pass. r3 must stay queued until a slot frees.
    cfg, params = model
    prompts = _prompts(cfg, [8, 4, 8, 4], seed=12)  # buckets: 8,4,8,4
    srv = GenerationServer(params, cfg, max_batch=3, max_len=32,
                           prefill_buckets=(4, 8))
    rids = [srv.submit(p, 6) for p in prompts]
    srv._admit()
    admitted = {r.rid for r in srv._slot_req if r is not None}
    assert admitted == set(rids[:3])  # the FIFO prefix, nothing skipped
    assert [r.rid for r in srv._queue] == [rids[3]]
    assert srv.stats()["prefill_batches"] == 1  # r0+r2 shared one forward
    res = srv.run()
    for p, rid in zip(prompts, rids):
        np.testing.assert_array_equal(res[rid], _oracle(params, cfg, p, 6, 32))


def test_batched_admission_kv_quant_bit_exact(model):
    # int8 arenas: each row quantizes per-vector, so the batched write is
    # bit-exact against the sequential one and tokens stay identical. The
    # reference side FORCES the sequential _fill_slot path (equal-length
    # prompts would otherwise group and batch there too, comparing the
    # batched path against itself).
    cfg, params = model
    prompts = _prompts(cfg, [6, 6, 6], seed=11)

    def run(buckets, can_batch):
        srv = GenerationServer(params, cfg, max_batch=2, max_len=32,
                               kv_quant=True, prefill_buckets=buckets)
        srv._can_batch_prefill = srv._can_batch_prefill and can_batch
        rids = [srv.submit(p, 8) for p in prompts]
        res = srv.run()
        return [res[r] for r in rids], srv

    ref, srv_seq = run(buckets=(), can_batch=False)
    out, srv_bat = run(buckets=(8,), can_batch=True)
    assert srv_seq.stats()["prefill_batches"] == 0  # sequential reference
    assert srv_bat.stats()["prefill_batches"] >= 1  # batched path engaged
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


# ----- persistent compilation cache ----------------------------------------


def test_persistent_cache_round_trip(tmp_path):
    """Second trace of the same executable hits the cache dir: entries
    appear after the first compile and the count does NOT grow on a
    recompile of the identical program (cache hit, not a rebuild)."""
    from kata_xpu_device_plugin_tpu.compat.jaxapi import (
        enable_compilation_cache,
    )

    cache_dir = str(tmp_path / "xla-cache")
    used = enable_compilation_cache(cache_dir, min_compile_time_s=0.0)
    if not used:  # pragma: no cover - jax line without the cache knob
        pytest.skip("persistent compilation cache unsupported on this jax")
    assert used == cache_dir
    try:
        fn = jax.jit(lambda x: (x * 3.0 - 1.0).sum())
        fn(jnp.arange(16.0)).block_until_ready()
        entries = set(os.listdir(cache_dir))
        assert entries, "first compile wrote no cache entries"
        jax.clear_caches()  # drop the in-memory executable: force a re-trace
        fn2 = jax.jit(lambda x: (x * 3.0 - 1.0).sum())
        fn2(jnp.arange(16.0)).block_until_ready()
        assert set(os.listdir(cache_dir)) == entries  # hit — nothing new
    finally:
        # Unpin the process-global cache dir so later tests compile
        # without touching the tmp dir.
        jax.config.update("jax_compilation_cache_dir", None)


def test_persistent_cache_kill_switch(tmp_path, monkeypatch):
    from kata_xpu_device_plugin_tpu.compat.jaxapi import (
        enable_compilation_cache,
    )

    monkeypatch.setenv("KATA_TPU_COMPILE_CACHE", "0")
    assert enable_compilation_cache(str(tmp_path / "never")) == ""
    assert not (tmp_path / "never").exists()


def test_persistent_cache_env_dir(tmp_path, monkeypatch):
    from kata_xpu_device_plugin_tpu.compat.jaxapi import (
        enable_compilation_cache,
    )

    env_dir = str(tmp_path / "from-env")
    monkeypatch.setenv("KATA_TPU_COMPILE_CACHE_DIR", env_dir)
    try:
        assert enable_compilation_cache() == env_dir
        assert os.path.isdir(env_dir)
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
