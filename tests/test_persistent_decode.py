"""Persistent on-device decode rounds + overlapped tp collectives
(ISSUE 20).

Oracle — THE WHILE_LOOP IS INVISIBLE IN THE OUTPUT: each delivered step
of the persistent executable is exactly the masked scan step PR 13
proved value-identical (greedy argmax, per-lane EOS/budget freeze as an
idempotent rewrite), so greedy outputs must be BIT-IDENTICAL to the
lock-step K=1 baseline across persistent on/off × tp{1,2} ×
paged/slotted × tp-overlap × prefix-hit × fused × seeded fault schedules
(± ``KATA_TPU_STRICT=1`` via ``make persistent``). The visible surfaces
are pinned separately: the loop's exit conditions (cap / done /
window — early exit when a live lane reaches its pre-reserved window),
dispatch-boundary-granular recovery, the env-degrade/explicit-raise knob
contract (``persistent_disabled``, never a crashed guest), the
always-present stats/heartbeat schema (``persistent`` /
``delivered_steps``), and the psum-scatter + all_gather decomposition's
exact numerics at tp=2.

Under ``make chaos`` this file also runs with
``KATA_TPU_FAULTS=decode_dispatch:4,sched_tick:3`` and a node-injected
``KATA_TPU_PERSISTENT=1`` — faults land MID-persistent-round and
recovery must stay invisible in every assertion below (tests pinning
the persistent default monkeypatch the env off).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest import tp_serving
from kata_xpu_device_plugin_tpu.guest.resilience import (
    FaultInjector,
    FaultSpec,
)
from kata_xpu_device_plugin_tpu.guest.serving import (
    ENV_PERSISTENT,
    GenerationServer,
    _persistent_serve_decode,
)
from kata_xpu_device_plugin_tpu.guest.tp_serving import (
    ENV_TP_OVERLAP,
    overlap_reduce_fn,
)
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    init_params,
    prefill,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=3):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


# Staggered budgets (the fused-suite precedent): equal ones synchronize
# lane finishes, so freezes would never land mid-persistent-round.
_LENS = [14, 9, 12, 7, 15, 11]
_BUDGETS = [6, 12, 9, 5, 11, 7]


def _serve(params, cfg, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("recovery_backoff_s", 0.0)
    if kw.pop("tp", 1) > 1:
        kw["mesh"] = tp_serving.serving_mesh(2)
    srv = GenerationServer(params, cfg, **kw)
    prompts = _prompts(cfg, _LENS)
    rids = [srv.submit(p, m) for p, m in zip(prompts, _BUDGETS)]
    res = srv.run()
    return [res[r] for r in rids], srv


# ----- bit-identity matrix ---------------------------------------------------


_MATRIX = [
    (dict(persistent=True), "slotted"),
    (dict(persistent=True, overlap=False), "slotted-lockstep"),
    (dict(persistent=True, decode_steps=4), "slotted-k4"),
    (dict(persistent=True, kv_pool_tokens=512, kv_block_size=8,
          kv_layout="blocks"), "paged"),
    (dict(persistent=True, strict=True), "strict"),
    (dict(persistent=True, tp=2), "tp2"),
]


@pytest.mark.parametrize(
    "kw", [c for c, _ in _MATRIX], ids=[i for _, i in _MATRIX]
)
def test_persistent_bit_identity(model, monkeypatch, kw):
    monkeypatch.delenv(ENV_PERSISTENT, raising=False)
    cfg, params = model
    base, _ = _serve(params, cfg)
    out, srv = _serve(params, cfg, **kw)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    st = srv.stats()
    assert st["persistent"] == 1
    assert st["persistent_rounds"] > 0
    assert st["delivered_steps_total"] > 0


def test_persistent_bit_identity_tp2_overlap(model, monkeypatch):
    # The full tentpole cross: persistent while_loop × tp=2 × the
    # psum-scatter/all_gather overlap hint. The decomposition reduces
    # the SAME partials in the same order, so greedy outputs stay
    # bit-identical to the single-chip baseline.
    monkeypatch.setenv(ENV_TP_OVERLAP, "1")
    cfg, params = model
    base, _ = _serve(params, cfg)
    out, _ = _serve(params, cfg, persistent=True, tp=2)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)


def test_persistent_with_fused_admissions(model):
    # ISSUE 20 + ISSUE 13: a round with a pending admission slice runs
    # the fused fixed-K dispatch, the others run persistent — one call
    # site, outputs identical to the unfused K=1 baseline.
    cfg, params = model
    base, _ = _serve(params, cfg)
    out, srv = _serve(params, cfg, persistent=True, fused=True,
                      sched_policy="slo_chunked", prefill_chunk=4,
                      itl_slo_ms=0.0)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["persistent_rounds"] > 0


# ----- exit conditions -------------------------------------------------------


def test_window_exhaustion_exits_early(model):
    # The loop's third exit: a live lane's next write would cross its
    # pre-reserved window — the executable must stop AT the window edge
    # (delivered < budget) instead of scribbling past the reservation.
    cfg, params = model
    B, max_len = 2, 32
    prompt = _prompts(cfg, [6])[0]
    caches, tok, _pos0 = prefill(
        params, jnp.asarray(np.stack([prompt, prompt])), cfg, max_len,
    )
    tok = jnp.asarray(tok, jnp.int32).reshape(B)
    pos = jnp.full((B,), len(prompt), jnp.int32)
    budget = jnp.asarray([20, 20], jnp.int32)
    # Lane 1's window ends 4 tokens ahead; lane 0's is ample.
    window = jnp.asarray([max_len, len(prompt) + 4], jnp.int32)
    out, _caches, _tok, new_pos, delivered = _persistent_serve_decode(
        params, caches, tok, pos, budget, window, cfg, 16,
    )
    assert int(delivered) == 4          # stopped at lane 1's window edge
    assert int(new_pos[1]) == len(prompt) + 4
    assert out.shape == (B, 16)         # dense carry stays cap-shaped


def test_cap_exit_bounds_the_round(model):
    # The heartbeat-cadence cap is a hard bound: budgets larger than the
    # static max_steps deliver exactly max_steps.
    cfg, params = model
    B, max_len = 2, 32
    prompt = _prompts(cfg, [6])[0]
    caches, tok, _pos0 = prefill(
        params, jnp.asarray(np.stack([prompt, prompt])), cfg, max_len,
    )
    tok = jnp.asarray(tok, jnp.int32).reshape(B)
    pos = jnp.full((B,), len(prompt), jnp.int32)
    out, _c, _t, _p, delivered = _persistent_serve_decode(
        params, caches, tok, pos, jnp.asarray([20, 20], jnp.int32),
        jnp.asarray([max_len, max_len], jnp.int32), cfg, 5,
    )
    assert int(delivered) == 5


def test_persistent_under_pool_pressure(model):
    # _ensure_blocks reserves the WHOLE persistent window up front, so a
    # tight pool preempts youngest-first at reservation time — outputs
    # must stay bit-identical through the spill/resume cycles.
    cfg, params = model
    base, _ = _serve(params, cfg)
    out, srv = _serve(params, cfg, persistent=True, kv_pool_tokens=128,
                      kv_block_size=8, kv_layout="blocks")
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["persistent_rounds"] > 0


def test_exit_reasons_partition_rounds(model, capture_events):
    cfg, params = model

    def run():
        return _serve(params, cfg, persistent=True)

    (_, srv), events = capture_events(run)
    st = srv.stats()
    exits = st["persistent_exits"]
    assert set(exits) == {"cap", "done", "window"}
    assert sum(exits.values()) == st["persistent_rounds"]
    evs = [e for e in events if e.get("name") == "persistent_exit"]
    assert len(evs) == st["persistent_rounds"]
    for e in evs:
        assert e["reason"] in exits
        assert 0 <= e["delivered"] <= e["cap"]
    assert st["delivered_steps_total"] == sum(e["delivered"] for e in evs)


# ----- recovery --------------------------------------------------------------


def test_persistent_recovery_identity(model):
    # A decode_dispatch fault interrupting a persistent round: the
    # donated partial dies with the failed dispatch, lanes replay
    # strict-FIFO from their prompts, and recovered greedy outputs stay
    # bit-identical — recovery is dispatch-boundary-granular, a
    # mid-while_loop fault never yields a half-applied round.
    cfg, params = model
    base, _ = _serve(params, cfg)
    inj = FaultInjector(schedule=(
        FaultSpec(seam="decode_dispatch", round=3),
        FaultSpec(seam="sched_tick", round=2),
    ), seed=7)
    out, srv = _serve(params, cfg, persistent=True, fault_injector=inj,
                      checkpoint_rounds=0)
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["recoveries"] >= 1
    assert not srv.failures()


# ----- knob contract ---------------------------------------------------------


@pytest.mark.parametrize("kw,needle", [
    (dict(speculative_k=2), "speculative"),
    (dict(ring_kv=True), "ring_kv"),
    (dict(temperature=0.8), "sampling"),
])
def test_explicit_persistent_conflict_raises(model, kw, needle):
    cfg, params = model
    if "ring_kv" in kw:
        cfg = tiny_test_config(dtype=jnp.float32, sliding_window=8)
        params = init_params(jax.random.PRNGKey(0), cfg,
                             dtype=jnp.float32)
    with pytest.raises(ValueError, match=needle):
        GenerationServer(params, cfg, max_batch=2, max_len=64,
                         persistent=True, **kw)


def test_env_persistent_conflict_degrades(model, monkeypatch,
                                          capture_events):
    # The daemon-injected env must never crash a guest whose config it
    # conflicts with: degrade with a persistent_disabled event.
    monkeypatch.setenv(ENV_PERSISTENT, "1")
    cfg, params = model

    def run():
        return GenerationServer(params, cfg, max_batch=2, max_len=64,
                                temperature=0.8)

    srv, events = capture_events(run)
    assert srv.stats()["persistent"] == 0
    evs = [e for e in events if e.get("name") == "persistent_disabled"]
    assert evs and evs[0]["reason"] == "sampling"


def test_env_persistent_malformed_degrades(model, monkeypatch,
                                           capture_events):
    monkeypatch.setenv(ENV_PERSISTENT, "maybe")
    cfg, params = model

    def run():
        return GenerationServer(params, cfg, max_batch=2, max_len=64)

    srv, events = capture_events(run)
    assert srv.stats()["persistent"] == 0
    evs = [e for e in events if e.get("name") == "persistent_disabled"]
    assert evs and evs[0]["reason"].startswith("bad_env")


def test_env_persistent_enables(model, monkeypatch):
    monkeypatch.setenv(ENV_PERSISTENT, "1")
    cfg, params = model
    base, _ = _serve(params, cfg, persistent=False)
    out, srv = _serve(params, cfg)          # env-enabled
    assert srv.stats()["persistent"] == 1
    for a, b in zip(base, out):
        np.testing.assert_array_equal(a, b)


# ----- stats / heartbeat schema ----------------------------------------------


def test_stats_schema_always_present(model, monkeypatch):
    # The no-schema-branch contract: every persistent field exists (as
    # zeros) on a server that never enables the loop.
    monkeypatch.delenv(ENV_PERSISTENT, raising=False)
    cfg, params = model
    _, srv = _serve(params, cfg)
    st = srv.stats()
    assert st["persistent"] == 0
    assert st["persistent_cap"] == 0
    assert st["persistent_rounds"] == 0
    assert st["delivered_steps"] == 0
    assert st["delivered_steps_total"] == 0
    assert st["persistent_exits"] == {"cap": 0, "done": 0, "window": 0}


def test_heartbeat_carries_persistent_fields(model, capture_events):
    cfg, params = model

    def run():
        return _serve(params, cfg, persistent=True, heartbeat_rounds=2)

    (_, srv), events = capture_events(run)
    hbs = [e for e in events if e.get("name") == "serving_heartbeat"]
    assert hbs
    for hb in hbs:
        assert hb["persistent"] == 1
        assert hb["delivered_steps"] >= 0
    assert any(hb["delivered_steps"] > 0 for hb in hbs)
    cfg_evs = [e for e in events if e.get("name") == "serving_config"]
    assert cfg_evs and cfg_evs[0]["persistent"] == 1
    assert cfg_evs[0]["persistent_cap"] == srv.stats()["persistent_cap"]


# ----- tp collective overlap -------------------------------------------------


def test_overlap_reduce_fn_gating(model, monkeypatch, capture_events):
    cfg, _ = model
    mesh = tp_serving.serving_mesh(2)
    monkeypatch.delenv(ENV_TP_OVERLAP, raising=False)
    # Default ON: the hint computes exactly the psum's value, so only
    # the explicit "0" kill switch (or an ineligible mesh/config)
    # forfeits the overlap.
    assert overlap_reduce_fn(mesh, cfg) is not None
    monkeypatch.setenv(ENV_TP_OVERLAP, "0")
    assert overlap_reduce_fn(mesh, cfg) is None
    monkeypatch.setenv(ENV_TP_OVERLAP, "1")
    assert overlap_reduce_fn(None, cfg) is None      # no mesh → no tp
    assert overlap_reduce_fn(mesh, cfg) is not None
    monkeypatch.setenv(ENV_TP_OVERLAP, "banana")

    def run():
        return overlap_reduce_fn(mesh, cfg)

    fn, events = capture_events(run)
    # Malformed values degrade to the DEFAULT (on) after one event —
    # a typo must not silently forfeit the overlap.
    assert fn is not None
    assert any(e.get("name") == "tp_overlap_disabled"
               and e["reason"].startswith("bad_env") for e in events)


def test_overlap_numerics_exact_at_tp2(model, monkeypatch):
    # The decomposed reduce (reduce-scatter + all-gather via the
    # sharding-constraint pair) sums the same per-shard partials in the
    # same order as the plain psum — greedy serving outputs at tp=2 must
    # be BIT-identical with the hint on vs off, fused and persistent
    # included.
    cfg, params = model
    monkeypatch.setenv(ENV_TP_OVERLAP, "0")
    plain, _ = _serve(params, cfg, tp=2)
    monkeypatch.setenv(ENV_TP_OVERLAP, "1")
    hinted, srv = _serve(params, cfg, tp=2)
    for a, b in zip(plain, hinted):
        np.testing.assert_array_equal(a, b)
    assert srv.stats()["steady_state_compiles"] == 0
