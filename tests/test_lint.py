"""Unit tests for ``tools.lint``: each rule has a positive fixture (must
fire) and a negative fixture (must stay quiet), plus pragma/scoping/CLI
behavior. Fixtures are linted via ``check_source`` under the repo-relative
path that puts them in the rule's scope."""
import subprocess
import sys

import pytest

from tools.lint import check_source
from tools.lint.cli import run

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OPS_PATH = "kata_xpu_device_plugin_tpu/ops/example.py"
COMPAT_PATH = "kata_xpu_device_plugin_tpu/compat/jaxapi.py"
TEST_PATH = "tests/test_example.py"
BENCH_PATH = "bench.py"


def rules_of(findings):
    return [f.rule for f in findings]


# ----- JX001: drifted symbol import -----------------------------------------


def test_jx001_fires_on_drifted_import():
    findings = check_source("from jax import shard_map\n", OPS_PATH)
    assert rules_of(findings) == ["JX001"]


def test_jx001_fires_on_drifted_sharding_import():
    findings = check_source(
        "from jax.sharding import AxisType\n", "kata_xpu_device_plugin_tpu/parallel/x.py"
    )
    assert rules_of(findings) == ["JX001"]


def test_jx001_fires_on_attribute_use():
    findings = check_source(
        "import jax\nn = jax.lax.axis_size('i')\n", OPS_PATH
    )
    assert "JX001" in rules_of(findings)


def test_jx001_quiet_on_compat_import():
    src = "from ..compat.jaxapi import shard_map\nfrom jax.sharding import Mesh\n"
    assert check_source(src, OPS_PATH) == []


def test_jx001_quiet_inside_compat():
    # compat/ is the one place allowed to touch the drifted surface.
    src = "from jax.experimental.shard_map import shard_map\n"
    assert check_source(src, COMPAT_PATH) == []


# ----- JX002: jax.experimental outside compat -------------------------------


def test_jx002_fires_on_experimental_import():
    findings = check_source(
        "from jax.experimental import mesh_utils\n", OPS_PATH
    )
    assert rules_of(findings) == ["JX002"]


def test_jx002_respects_pragma():
    src = (
        "from jax.experimental import pallas as pl"
        "  # lint: allow(JX002) pallas-only API\n"
    )
    assert check_source(src, OPS_PATH) == []


# ----- JX003: float64 in TPU-path code --------------------------------------


def test_jx003_fires_on_float64_dtype():
    findings = check_source(
        "import jax.numpy as jnp\nx = jnp.zeros((4,), jnp.float64)\n", OPS_PATH
    )
    assert rules_of(findings) == ["JX003"]


def test_jx003_fires_on_float64_string():
    findings = check_source(
        "def f(a):\n    return a.astype('float64')\n", OPS_PATH
    )
    assert rules_of(findings) == ["JX003"]


def test_jx003_quiet_on_float32_and_out_of_scope():
    ok = "import jax.numpy as jnp\nx = jnp.zeros((4,), jnp.float32)\n"
    assert check_source(ok, OPS_PATH) == []
    # float64 in host-side plugin code is not TPU-path — out of scope.
    host = "import numpy as np\nx = np.float64(3)\n"
    assert check_source(host, "kata_xpu_device_plugin_tpu/plugin/manager.py") == []


# ----- JX004: unfenced timing loops -----------------------------------------

_TIMED_UNFENCED = """
import time

def run(f, x):
    t0 = time.perf_counter()
    y = f(x)
    return time.perf_counter() - t0, y
"""

_TIMED_FENCED = """
import time
import jax

def run(f, x):
    t0 = time.perf_counter()
    y = jax.block_until_ready(f(x))
    return time.perf_counter() - t0, y
"""

_TIMED_TRANSFER_FENCED = """
import time
import numpy as np

def run(f, x):
    t0 = time.perf_counter()
    y = np.asarray(f(x))
    return time.perf_counter() - t0, y
"""


def test_jx004_fires_on_unfenced_timing():
    findings = check_source(_TIMED_UNFENCED, BENCH_PATH)
    assert rules_of(findings) == ["JX004"]


def test_jx004_quiet_when_fenced():
    assert check_source(_TIMED_FENCED, BENCH_PATH) == []
    # A device→host transfer of the result is an equally hard fence.
    assert check_source(_TIMED_TRANSFER_FENCED, BENCH_PATH) == []


_TIMED_NESTED = """
import time
import jax

def outer(f, x):
    # two unfenced timers HERE; the fence lives only in a nested callback
    # that may never run inline — it must not excuse the outer loop.
    def cb(y):
        return jax.block_until_ready(y)
    t0 = time.perf_counter()
    y = f(x, cb)
    return time.perf_counter() - t0, y

def helper(f, x):
    # no timers of its own: only the nested def times, and it fences.
    def timed(z):
        t0 = time.perf_counter()
        out = jax.block_until_ready(f(z))
        return time.perf_counter() - t0, out
    return timed(x)
"""


def test_jx004_nested_defs_scored_separately():
    findings = check_source(_TIMED_NESTED, BENCH_PATH)
    # 'outer' fires (its fence is inside a nested callback); 'cb', 'timed'
    # and 'helper' are each clean on their own.
    assert [(f.rule, f.line) for f in findings] == [("JX004", 5)]


def test_jx004_out_of_scope_outside_bench():
    # Timing in ordinary library code is not the bench rule's business —
    # since ISSUE 2 it is JX005's (use obs.span/obs.timer), not JX004's.
    findings = check_source(
        _TIMED_UNFENCED, "kata_xpu_device_plugin_tpu/utils/log.py"
    )
    assert rules_of(findings) == ["JX005"]
    assert check_source(
        _TIMED_UNFENCED, "kata_xpu_device_plugin_tpu/utils/log.py",
        rules=["JX004"],
    ) == []


# ----- JX005: raw timing in library code ------------------------------------

_LIB_PATH = "kata_xpu_device_plugin_tpu/guest/serving.py"
_OBS_PATH = "kata_xpu_device_plugin_tpu/obs/trace.py"


def test_jx005_fires_on_library_timing_window():
    findings = check_source(_TIMED_UNFENCED, _LIB_PATH)
    assert rules_of(findings) == ["JX005"]


def test_jx005_fires_even_when_fenced():
    # JX004's escape hatch (a fence) does not apply: library code must use
    # obs.span/obs.timer so the measurement lands in the pipeline, not a
    # local variable.
    assert rules_of(check_source(_TIMED_FENCED, _LIB_PATH)) == ["JX005"]
    assert rules_of(check_source(_TIMED_TRANSFER_FENCED, _LIB_PATH)) == [
        "JX005"
    ]


def test_jx005_quiet_on_single_timestamp():
    # One timer call is a timestamp (e.g. stamping a request's submit
    # time), not a timing window.
    src = (
        "import time\n"
        "def submit(q, req):\n"
        "    req.t_submit = time.monotonic()\n"
        "    q.append(req)\n"
    )
    assert check_source(src, _LIB_PATH) == []


def test_jx005_out_of_scope_in_obs_and_bench():
    # obs/ implements the timer — it is the one library place allowed raw
    # perf_counter pairs; bench files stay under JX004's fence rule.
    assert check_source(_TIMED_UNFENCED, _OBS_PATH) == []
    assert rules_of(check_source(_TIMED_UNFENCED, BENCH_PATH)) == ["JX004"]
    assert check_source(_TIMED_FENCED, BENCH_PATH) == []
    # ...and plain tools/tests code is neither scope.
    assert check_source(_TIMED_UNFENCED, "tools/lint/cli.py") == []


def test_jx005_respects_pragma():
    src = _TIMED_UNFENCED.replace(
        "def run(f, x):",
        "def run(f, x):  # lint: allow(JX005) wall-clock only, no device work",
    )
    # The pragma sits on the function's own line, where the finding anchors.
    findings = check_source(src, _LIB_PATH)
    assert findings == []


# ----- TS001: non-hermetic tests --------------------------------------------


def test_ts001_fires_on_dev_probe():
    findings = check_source(
        "import os\nok = os.path.exists('/dev/accel0')\n", TEST_PATH
    )
    assert rules_of(findings) == ["TS001"]


def test_ts001_fires_on_network_call():
    findings = check_source(
        "import urllib.request\nurllib.request.urlopen('http://x')\n", TEST_PATH
    )
    assert rules_of(findings) == ["TS001"]


def test_ts001_quiet_on_fake_roots_and_literals():
    # Asserting on a /dev/... *string* (e.g. a CDI spec's declared path) is
    # fine — only filesystem probes against the real tree are flagged.
    src = (
        "def test_x(tmp_path):\n"
        "    p = tmp_path / 'accel0'\n"
        "    assert str(p).endswith('accel0')\n"
        "    expected = '/dev/accel0'\n"
        "    assert expected == '/dev/accel0'\n"
    )
    assert check_source(src, TEST_PATH) == []


# ----- plumbing --------------------------------------------------------------


def test_syntax_error_reported_not_raised():
    findings = check_source("def broken(:\n", OPS_PATH)
    assert rules_of(findings) == ["E999"]


def test_rule_filter():
    src = "from jax import shard_map\nfrom jax.experimental import pallas\n"
    only_jx002 = check_source(src, OPS_PATH, rules=["JX002"])
    assert rules_of(only_jx002) == ["JX002"]


def test_repo_is_lint_clean():
    """The acceptance bar: the linter exits clean on this repo."""
    assert run(root=None) == []


def test_cli_red_on_seed_bug(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import shard_map\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", str(bad), "--root", str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "JX001" in proc.stdout


def test_cli_list_rules():
    """--list-rules prints BOTH catalogues: the per-function lint rules
    and the jaxguard dataflow rules (ISSUE 4 satellite)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--list-rules"],
        capture_output=True, text=True, cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0
    for rule in ("JX001", "JX002", "JX003", "JX004", "JX005", "TS001",
                 "JG101", "JG102", "JG103", "JG104"):
        assert rule in proc.stdout


def test_pragma_multi_rule_and_shared_grammar():
    """allow(RULE[, RULE...]) takes a list, and the grammar is shared
    with jaxguard (tools.pragmas): a `# jaxguard:` prefix suppresses
    lint rules too — ids are globally unique, the prefix is
    documentation."""
    src = (
        "from jax import shard_map"
        "  # lint: allow(JX001, JX002) fixture exercising the list form\n"
    )
    assert check_source(src, OPS_PATH) == []
    src2 = (
        "from jax.experimental import mesh_utils"
        "  # jaxguard: allow(JX002) cross-prefix suppression\n"
    )
    assert check_source(src2, OPS_PATH) == []
