"""Unit tests for the unified telemetry layer (ISSUE 2): span
nesting/fencing, registry injection + re-import safety, trainer-step and
serving emission on tiny CPU models, profiler-hook windowing, and the
JSONL sink round-trip.
"""
from __future__ import annotations

import importlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from prometheus_client import REGISTRY, CollectorRegistry, generate_latest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.obs import events as obs_events
from kata_xpu_device_plugin_tpu.obs import trace as obs_trace


@pytest.fixture
def sink(tmp_path):
    """A fresh default sink writing under tmp_path; restores the previous
    default afterwards (the default is process state)."""
    path = str(tmp_path / "events.jsonl")
    s = obs.EventSink(path)
    prev = obs.set_default_sink(s)
    yield s, path
    s.close()
    obs.set_default_sink(prev)


def read(path):
    return obs.read_events(path)


# ----- spans: nesting, fencing, decorator -----------------------------------


def test_span_nesting_ids_and_parents(sink):
    s, path = sink
    with obs.span("outer") as o:
        assert obs_trace.current_span_id() == o.span_id
        with obs.span("inner") as i:
            assert i.trace_id == o.trace_id  # one trace
            assert i.parent_id == o.span_id
            assert obs_trace.current_span_id() == i.span_id
        assert obs_trace.current_span_id() == o.span_id
    assert obs_trace.current_span_id() is None
    evs = {e["name"]: e for e in read(path)}
    # inner closes first; both carry the shared trace and the link.
    assert evs["inner"]["parent"] == evs["outer"]["span"]
    assert evs["inner"]["trace"] == evs["outer"]["trace"]
    assert evs["outer"]["parent"] is None
    assert evs["outer"]["dur_s"] >= evs["inner"]["dur_s"] >= 0


def test_span_fences_registered_values(sink, monkeypatch):
    fenced = []
    monkeypatch.setattr(obs_trace, "_block_until_ready", fenced.append)
    x = jnp.ones((4,))
    with obs.span("work") as sp:
        assert sp.fence(x) is x  # pass-through for expression use
    with obs.span("arg-form", fence=lambda: "late"):
        pass
    assert fenced == [x, "late"]


def test_span_fence_real_jax_value(sink):
    # End to end with the real fence: a jitted result registered via
    # fence() must not error, and the duration is recorded after the wait.
    with obs.span("jit") as sp:
        y = jax.jit(lambda a: a * 2)(jnp.arange(8))
        sp.fence(y)
    assert sp.duration_s > 0


def test_span_fence_error_surfaces_without_masking(sink, monkeypatch):
    s, path = sink

    def explode(_value):
        raise RuntimeError("deferred device error")

    monkeypatch.setattr(obs_trace, "_block_until_ready", explode)
    # Success-path body: the fence's deferred error must propagate (after
    # the span's bookkeeping — the event is still emitted and the stack
    # unwound).
    with pytest.raises(RuntimeError, match="deferred device error"):
        with obs.span("fenced") as sp:
            sp.fence(jnp.ones(2))
    assert obs_trace.current_span_id() is None
    # Failing body: the body's exception wins; the fence error must not
    # mask it (and the up-front fence callable is not even resolved).
    with pytest.raises(ValueError, match="body wins"):
        with obs.span("both", fence=lambda: 1 / 0):
            raise ValueError("body wins")
    evs = {e["name"]: e for e in read(path)}
    assert evs["fenced"]["error"].startswith("RuntimeError")
    assert evs["both"]["error"].startswith("ValueError")


def test_span_fence_resolver_error_still_closes_span(sink):
    s, path = sink
    # A raising up-front fence RESOLVER must surface its error AND still
    # close the span (context unwound, event emitted) — a dead span left
    # installed would corrupt every later span's parent/trace.
    with pytest.raises(ZeroDivisionError):
        with obs.span("resolver-fails", fence=lambda: 1 / 0):
            pass
    assert obs_trace.current_span_id() is None
    (ev,) = read(path)
    assert ev["name"] == "resolver-fails"
    assert ev["error"].startswith("ZeroDivisionError")
    # ...and a clean nested span afterwards starts a fresh trace.
    with obs.span("after") as sp:
        assert sp.parent_id is None


def test_span_error_recorded_and_reraised(sink):
    s, path = sink
    with pytest.raises(ValueError, match="boom"):
        with obs.span("fails"):
            raise ValueError("boom")
    (ev,) = read(path)
    assert ev["error"].startswith("ValueError: boom")
    assert obs_trace.current_span_id() is None  # stack unwound


def test_span_tokens_per_s_derived(sink):
    import time as _time

    s, path = sink
    with obs.span("step", tokens=1000):
        _time.sleep(0.02)  # dwarf the 1µs dur_s rounding granularity
    (ev,) = read(path)
    assert ev["tokens"] == 1000
    assert ev["tokens_per_s"] == pytest.approx(1000 / ev["dur_s"], rel=0.05)


def test_traced_decorator(sink):
    s, path = sink

    @obs.traced()
    def double(a):
        return a * 2

    out = double(jnp.arange(4))
    np.testing.assert_array_equal(np.asarray(out), [0, 2, 4, 6])
    (ev,) = read(path)
    assert ev["name"].endswith("double")


def test_timer_feeds_metric(sink):
    rolling = obs.Rolling()
    with obs.timer("t", metric=rolling):
        pass
    with obs.timer("t", metric=rolling):
        pass
    summ = rolling.summary()
    assert summ["count"] == 2
    assert summ["min"] <= summ["p50"] <= summ["max"]


def test_disabled_sink_is_noop(tmp_path):
    prev = obs.set_default_sink(None)
    try:
        with obs.span("quiet") as sp:
            pass
        assert sp.duration_s is not None  # still timed, just not emitted
        assert obs.emit("x", "y") is None
    finally:
        obs.set_default_sink(prev)


# ----- metrics registry ------------------------------------------------------


def test_registry_injection_and_idempotence():
    reg = obs.MetricsRegistry(CollectorRegistry())
    c1 = reg.counter("things_total", "Things", ["kind"])
    c2 = reg.counter("things_total", "Things", ["kind"])
    assert c1 is c2
    c1.labels(kind="a").inc(3)
    text = generate_latest(reg.registry).decode()
    assert 'things_total{kind="a"} 3.0' in text


def test_registry_adopts_after_cache_loss():
    # A NEW MetricsRegistry over the same CollectorRegistry (the reload
    # scenario: module cache gone, prometheus registry persists) must
    # adopt, not re-register.
    prom = CollectorRegistry()
    a = obs.MetricsRegistry(prom).counter("x_total", "d")
    b = obs.MetricsRegistry(prom).counter("x_total", "d")
    assert a is b
    g1 = obs.MetricsRegistry(prom).gauge("g", "d", ["l"])
    g2 = obs.MetricsRegistry(prom).gauge("g", "d", ["l"])
    assert g1 is g2


def test_registry_type_and_label_mismatch_raises():
    reg = obs.MetricsRegistry(CollectorRegistry())
    reg.counter("m_total", "d", ["a"])
    with pytest.raises(ValueError, match="already exists"):
        reg.gauge("m_total", "d", ["a"])
    with pytest.raises(ValueError, match="already exists"):
        reg.counter("m_total", "d", ["b"])


def test_utils_metrics_reimport_safe():
    """The satellite bug: importing utils.metrics twice (or after any other
    module registered the same names) used to raise Duplicated timeseries."""
    from kata_xpu_device_plugin_tpu.utils import metrics as um

    before = um.allocations_total
    um2 = importlib.reload(um)
    assert um2.allocations_total is before  # adopted, not re-registered
    importlib.reload(um2)  # and again, for good measure


def test_rolling_summary_quantiles():
    r = obs.Rolling(keep=100)
    for v in range(1, 101):
        r.observe(v / 100)
    s = r.summary()
    assert s["count"] == 100
    assert s["min"] == 0.01 and s["max"] == 1.0
    assert 0.45 <= s["p50"] <= 0.55
    assert 0.90 <= s["p95"] <= 1.0
    assert obs.Rolling().summary() == {"count": 0}


# Percentile EDGES (ISSUE 8 satellite): the latency-under-load bench
# section reports TTFT/ITL p50/p99 straight from these summaries, so the
# estimator's boundary behavior is now a consumed contract, not an
# implementation detail.


def test_rolling_empty_window_summary_and_quantiles():
    r = obs.Rolling()
    # Empty: the summary is the {"count": 0} sentinel (no fake zeros a
    # dashboard could mistake for a measured latency)...
    assert r.summary() == {"count": 0}
    # ...and the raw quantile helper answers 0.0 rather than raising.
    assert r._quantile(0.5) == 0.0
    assert r._quantile(0.99) == 0.0


def test_rolling_single_sample_all_quantiles_collapse():
    r = obs.Rolling()
    r.observe(0.25)
    s = r.summary()
    assert s["count"] == 1
    # One sample IS every order statistic.
    assert (s["min"] == s["max"] == s["mean"]
            == s["p50"] == s["p95"] == s["p99"] == 0.25)


def test_rolling_exact_quantile_boundaries():
    # Pin the nearest-rank rule on exactly-hit boundaries:
    # idx = min(n-1, int(q*(n-1) + 0.5)) over the SORTED window.
    r = obs.Rolling(keep=100)
    for v in range(1, 101):  # 1..100 — value = rank + 1 at 0-based idx
        r.observe(float(v))
    s = r.summary()
    # q*(n-1) lands exactly on 49.5 for p50 → rounds to idx 50 → value 51.
    assert s["p50"] == 51.0
    # p95: int(0.95*99 + 0.5) = int(94.55) = 94 → value 95.
    assert s["p95"] == 95.0
    # p99: int(0.99*99 + 0.5) = int(98.51) = 98 → value 99 (NOT the max —
    # the rank rule never extrapolates past the window).
    assert s["p99"] == 99.0
    # Two samples: p50 rounds UP to the larger (idx min(1, int(1.0)) = 1).
    r2 = obs.Rolling()
    r2.observe(1.0)
    r2.observe(2.0)
    assert r2.summary()["p50"] == 2.0


def test_rolling_window_eviction_keeps_cumulative_count():
    # The reservoir is bounded (recent-window quantiles) while count/mean
    # stay cumulative — the stats() contract serving documents.
    r = obs.Rolling(keep=4)
    for v in (100.0, 100.0, 1.0, 2.0, 3.0, 4.0):
        r.observe(v)
    s = r.summary()
    assert s["count"] == 6          # cumulative
    assert s["max"] == 100.0        # cumulative extrema survive eviction
    assert s["p99"] == 4.0          # quantiles see only the kept window
    assert s["p50"] == 3.0          # sorted window [1,2,3,4] → idx 2


# ----- trainer emission ------------------------------------------------------


def test_trainer_step_metrics_on_tiny_model(sink):
    s, path = sink
    from kata_xpu_device_plugin_tpu.models import llama3_train_test
    from kata_xpu_device_plugin_tpu.parallel import (
        build_mesh,
        fit,
        make_loader,
        make_train_step,
    )

    cfg = llama3_train_test()
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    init_state, step = make_train_step(cfg, mesh, aux_metrics=True)
    loader = make_loader(
        np.arange(4096, dtype=np.int32) % cfg.vocab_size,
        batch=8, seq_len=31, mesh=mesh, seed=5,
    )
    state, losses = fit(init_state, step, loader, steps=3,
                        key=jax.random.PRNGKey(0))
    assert len(losses) == 3

    evs = read(path)
    steps = [e for e in evs if e["name"] == "train.step"]
    assert len(steps) == 3
    assert steps[0]["includes_compile"] is True
    assert "includes_compile" not in steps[1]
    for i, ev in enumerate(steps):
        assert ev["step"] == i + 1
        assert ev["tokens"] == 8 * 32  # batch × (seq_len + 1) token window
        assert ev["tokens_per_s"] > 0
        assert np.isfinite(ev["loss"])
        assert ev["grad_norm"] > 0  # aux_metrics contract
        assert ev["dur_s"] > 0
    # losses in events must equal fit()'s returned series.
    np.testing.assert_allclose([e["loss"] for e in steps], losses, rtol=1e-5)

    (est,) = [e for e in evs if e["name"] == "train.compile_estimate"]
    assert est["first_step_s"] >= est["steady_step_s"] > 0
    assert est["dur_s"] == pytest.approx(
        est["first_step_s"] - est["steady_step_s"], abs=1e-5
    )

    # Prometheus side: the gauges/histogram carry the last step.
    text = generate_latest(REGISTRY).decode()
    assert "kata_tpu_train_step_seconds_bucket" in text
    assert f"kata_tpu_train_loss {losses[-1]}" in text


def test_trainer_uninstrumented_path_unchanged():
    """With no sink, fit() must not emit, sync per step, or alter the
    (state, loss) contract — including the 3-tuple aux form."""
    from kata_xpu_device_plugin_tpu.parallel.trainer import _unpack_step

    assert _unpack_step(("s", 1.0)) == ("s", 1.0, {})
    assert _unpack_step(("s", 1.0, {"grad_norm": 2.0})) == (
        "s", 1.0, {"grad_norm": 2.0}
    )


# ----- serving emission ------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from kata_xpu_device_plugin_tpu.models import tiny_test_config
    from kata_xpu_device_plugin_tpu.models.transformer import init_params

    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _submit_prompts(srv, cfg, lengths, budget=8, seed=9):
    key = jax.random.PRNGKey(seed)
    return [
        srv.submit(
            np.asarray(
                jax.random.randint(
                    jax.random.fold_in(key, i), (n,), 0, cfg.vocab_size
                ),
                np.int32,
            ),
            budget,
        )
        for i, n in enumerate(lengths)
    ]


def test_serving_ttft_and_queue_metrics(sink, tiny_model):
    s, path = sink
    from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

    cfg, params = tiny_model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4)
    rids = _submit_prompts(srv, cfg, [4, 7, 5, 6])  # queue pressure: 4 → 2 slots
    results = srv.run()
    assert set(results) == set(rids)

    st = srv.stats()
    assert st["ttft_s"]["count"] == 4  # one TTFT per request
    assert st["ttft_s"]["min"] > 0
    assert st["decode_token_s"]["count"] == st["rounds"]
    assert st["decode_token_s"]["mean"] > 0
    assert st["batch_occupancy"] == 0.0 and st["kv_slot_utilization"] == 0.0

    evs = read(path)
    ttfts = [e for e in evs if e["name"] == "ttft"]
    assert len(ttfts) == 4
    # The 3rd/4th requests waited in the queue — their events say so.
    assert any(e["queued"] > 0 for e in ttfts)
    chunks = [e for e in evs if e["name"] == "serving.decode_chunk"]
    assert len(chunks) == st["rounds"]
    for c in chunks:
        assert c["slots_busy"] >= 1
        assert 0 < c["batch_occupancy"] <= 1.0
        if c.get("overlapped"):
            # Pipelined rounds (ISSUE 3): chunk_tokens + the dispatch/fence
            # split, with the rate anchored to the retire cadence round_s
            # (dur_s is the in-flight pipeline window, not a denominator).
            assert c["chunk_tokens"] == c["slots_busy"] * 4  # chunk=4
            assert 0 <= c["dispatch_s"] <= c["dur_s"]
            assert c["round_s"] > 0
        else:
            assert c["tokens"] == c["slots_busy"] * 4  # chunk=4
    prefills = [e for e in evs if e["name"] == "serving.prefill"]
    assert len(prefills) == 4


def test_serving_stats_snapshot_semantics(tiny_model):
    """stats() is a cumulative SNAPSHOT: calling it never resets anything,
    and counters keep growing across successive run() batches."""
    from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

    cfg, params = tiny_model
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32)
    _submit_prompts(srv, cfg, [4, 6], budget=5)
    srv.run()
    st1 = srv.stats()
    assert srv.stats() == st1  # idle snapshot is stable
    _submit_prompts(srv, cfg, [5], budget=5, seed=10)
    srv.run()
    st2 = srv.stats()
    assert st2["prefills"] == st1["prefills"] + 1
    assert st2["tokens_emitted"] > st1["tokens_emitted"]
    assert st2["ttft_s"]["count"] == st1["ttft_s"]["count"] + 1


def test_serving_speculative_round_events(sink, tiny_model):
    s, path = sink
    from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

    cfg, params = tiny_model
    rep = np.tile(np.array([5, 17], np.int32), 6)
    srv = GenerationServer(params, cfg, max_batch=1, max_len=40,
                           speculative_k=3)
    srv.submit(rep, max_new_tokens=10)
    srv.run()
    rounds = [e for e in read(path) if e["name"] == "spec_round"]
    assert rounds and all(r["accepted"] >= 1 for r in rounds)
    assert all(r["offered"] == 3 for r in rounds)
    assert srv.stats()["decode_token_s"]["count"] == len(rounds)


def test_serving_histograms_exported(tiny_model):
    from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer

    cfg, params = tiny_model
    srv = GenerationServer(params, cfg, max_batch=1, max_len=32)
    lbl = srv.export_metrics()
    _submit_prompts(srv, cfg, [5], budget=6, seed=12)
    srv.run()
    text = generate_latest(REGISTRY).decode()
    assert f'kata_tpu_serving_ttft_seconds_count{{server="{lbl}"}} 1.0' in text
    assert "kata_tpu_serving_decode_token_seconds_bucket" in text
    # New occupancy gauges ride the same scrape.
    assert f'kata_tpu_serving_batch_occupancy{{server="{lbl}"}}' in text
    assert f'kata_tpu_serving_kv_slot_utilization{{server="{lbl}"}}' in text


# ----- JSONL sink ------------------------------------------------------------


def test_event_sink_round_trip(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with obs.EventSink(path, clock=lambda: 123.0) as s:
        s.emit("span", "a", dur_s=0.5, n=1)
        s.emit("serving", "ttft", ttft_s=0.01, arr=np.int32(7))
    evs = read(path)
    assert evs == [
        {"ts": 123.0, "kind": "span", "name": "a", "dur_s": 0.5, "n": 1},
        {"ts": 123.0, "kind": "serving", "name": "ttft", "ttft_s": 0.01,
         "arr": 7},  # numpy scalars serialize as plain numbers
    ]
    assert s.emitted == 2


def test_event_sink_appends_and_tolerates_torn_line(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with obs.EventSink(path) as s:
        s.emit("span", "a")
    with open(path, "a") as fh:
        fh.write('{"torn": ')  # killed writer mid-line
    with obs.EventSink(path) as s2:  # append, not truncate
        s2.emit("span", "b")
    names = [e["name"] for e in read(path)]
    assert names == ["a", "b"]


def test_read_events_offset_isolates_a_run(tmp_path):
    """The bench worker records the pre-run file size and reads from that
    offset — a pinned KATATPU_OBS_FILE carrying earlier runs' spans must
    not pollute the new run's phase aggregation."""
    import os

    path = str(tmp_path / "shared.jsonl")
    with obs.EventSink(path) as s:
        s.emit("span", "bench.decode", dur_s=1.0)  # previous run
    offset = os.path.getsize(path)
    with obs.EventSink(path) as s2:
        s2.emit("span", "bench.decode", dur_s=2.0)  # this run
    assert [e["dur_s"] for e in read(path)] == [1.0, 2.0]
    this_run = obs.read_events(path, offset=offset)
    assert [e["dur_s"] for e in this_run] == [2.0]
    assert obs.summarize_phases(this_run, prefix="bench.")["decode"]["count"] == 1


def test_tail_events_incremental(tmp_path):
    """The ISSUE 15 poller contract: each call returns only the events
    past the previous offset, and the returned offset resumes exactly —
    the watchdog/daemon-aggregator/bench_watch loops stop re-reading
    whole files every poll."""
    path = str(tmp_path / "tail.jsonl")
    assert obs.tail_events(path) == ([], 0)  # missing file: steady state
    with obs.EventSink(path) as s:
        s.emit("serving", "a")
        s.emit("serving", "b")
    evs, off = obs.tail_events(path)
    assert [e["name"] for e in evs] == ["a", "b"]
    assert off == os.path.getsize(path)
    assert obs.tail_events(path, off) == ([], off)  # nothing new
    with obs.EventSink(path) as s:
        s.emit("serving", "c")
    evs2, off2 = obs.tail_events(path, off)
    assert [e["name"] for e in evs2] == ["c"]
    assert off2 == os.path.getsize(path)


def test_tail_events_leaves_torn_tail_unconsumed(tmp_path):
    path = str(tmp_path / "torn.jsonl")
    with obs.EventSink(path) as s:
        s.emit("serving", "a")
    with open(path, "a") as fh:
        fh.write('{"kind": "serving", "name": "part')  # writer mid-line
    evs, off = obs.tail_events(path)
    assert [e["name"] for e in evs] == ["a"]
    assert off < os.path.getsize(path)  # torn bytes not consumed
    with open(path, "a") as fh:
        fh.write('ial"}\n')  # writer completes the line
    evs2, off2 = obs.tail_events(path, off)
    assert [e["name"] for e in evs2] == ["partial"]
    assert off2 == os.path.getsize(path)


def test_tail_events_rotation_restarts(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    with obs.EventSink(path) as s:
        s.emit("serving", "old1")
        s.emit("serving", "old2")
    _, off = obs.tail_events(path)
    # Rotation: the file is truncated and a new stream starts — the
    # tail must restart from 0, not hang past-EOF forever.
    os.truncate(path, 0)
    with obs.EventSink(path) as s:
        s.emit("serving", "fresh")
    evs, off2 = obs.tail_events(path, off)
    assert [e["name"] for e in evs] == ["fresh"]
    assert off2 == os.path.getsize(path)


def test_tail_events_truncate_then_regrow_restarts(tmp_path):
    """copytruncate-style rotation where the new stream regrows PAST
    the old offset between polls: the stale offset no longer sits on a
    line boundary, so the tail restarts from 0 instead of splicing
    mid-line into the new content."""
    path = str(tmp_path / "regrow.jsonl")
    with obs.EventSink(path) as s:
        s.emit("serving", "old")
    _, off = obs.tail_events(path)
    os.truncate(path, 0)
    with obs.EventSink(path) as s:
        # Longer than the old stream, and the byte at off-1 is mid-line.
        s.emit("serving", "new1", pad="x" * 256)
        s.emit("serving", "new2")
    evs, off2 = obs.tail_events(path, off)
    assert [e["name"] for e in evs] == ["new1", "new2"]
    assert off2 == os.path.getsize(path)


def test_tail_events_skips_corrupt_complete_lines(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as fh:
        fh.write('not json at all\n{"kind": "serving", "name": "ok"}\n')
    evs, off = obs.tail_events(path)
    assert [e["name"] for e in evs] == ["ok"]
    # Corrupt-but-complete bytes ARE consumed — the tail never wedges.
    assert off == os.path.getsize(path)


def test_summarize_phases():
    evs = [
        {"kind": "span", "name": "bench.decode", "dur_s": 0.2},
        {"kind": "span", "name": "bench.decode", "dur_s": 0.4},
        {"kind": "span", "name": "bench.compile", "dur_s": 2.0},
        {"kind": "span", "name": "serving.prefill", "dur_s": 9.0},  # filtered
        {"kind": "serving", "name": "bench.decode"},  # not a span
    ]
    out = obs.summarize_phases(evs, prefix="bench.")
    assert set(out) == {"decode", "compile"}
    assert out["decode"] == {
        "count": 2, "total_s": 0.6, "min_s": 0.2, "max_s": 0.4, "mean_s": 0.3,
    }
    assert out["compile"]["count"] == 1


def test_configure_from_env(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("KATATPU_OBS", "1")
    monkeypatch.setenv("KATATPU_OBS_FILE", path)
    assert obs.enabled()
    prev_sink = obs_events._default if obs_events._configured else None
    try:
        s = obs.configure_from_env(force=True)
        assert s is not None and s.path == path
        obs.emit("span", "via-env", dur_s=0.1)
        assert [e["name"] for e in read(path)] == ["via-env"]
    finally:
        obs.set_default_sink(prev_sink)
    monkeypatch.delenv("KATATPU_OBS")
    assert obs.configure_from_env(force=True) is None
    obs.set_default_sink(prev_sink)


def test_log_records_carry_trace_ids(sink, capsys):
    import logging

    from kata_xpu_device_plugin_tpu.utils import log

    logger = logging.getLogger(log.ROOT)
    saved = (logger.level, logger.propagate, list(logger.handlers))
    log.setup("info", "json")
    try:
        with obs.span("handler") as sp:
            logger.info("inside", extra=log.kv(k="v"))
        logger.info("outside")
        err = capsys.readouterr().err.strip().splitlines()
        inside, outside = (json.loads(line) for line in err[-2:])
        assert inside["trace"] == sp.trace_id
        assert inside["span"] == sp.span_id
        assert inside["k"] == "v"
        assert "trace" not in outside
    finally:
        # setup() reconfigures the process-global "katatpu" logger tree
        # (propagate=False, stderr handler); restore it or later tests'
        # caplog (which relies on propagation to root) goes blind.
        logger.handlers.clear()
        logger.handlers.extend(saved[2])
        logger.setLevel(saved[0])
        logger.propagate = saved[1]


# ----- profiler hook ---------------------------------------------------------


@pytest.fixture
def fake_profiler(monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    return calls


def test_profiler_hook_window(tmp_path, fake_profiler, sink):
    s, path = sink
    d = str(tmp_path / "prof")
    hook = obs.ProfilerHook(d, start_step=2, num_steps=3)
    for step in range(1, 7):
        hook.on_step(step)
    assert fake_profiler == [("start", d), ("stop",)]
    hook.on_step(1)  # window done: never restarts
    assert len(fake_profiler) == 2
    (ev,) = [e for e in read(path) if e["kind"] == "profile"]
    assert ev["start_step"] == 2 and ev["stop_step"] == 4


def test_profiler_hook_start_step_one_and_resume(tmp_path, fake_profiler):
    # start_step=1: the trainer primes with on_step(resume_step) before the
    # loop, so the window opens before the first executed step.
    hook = obs.ProfilerHook(str(tmp_path / "a"), start_step=1, num_steps=2)
    for step in (0, 1, 2, 3):  # fit() primes with 0, then steps 1..3
        hook.on_step(step)
    assert fake_profiler == [("start", str(tmp_path / "a")), ("stop",)]
    fake_profiler.clear()
    # Resume landing INSIDE the window [3, 5] still opens it...
    hook = obs.ProfilerHook(str(tmp_path / "b"), start_step=3, num_steps=3)
    for step in (4, 5, 6):
        hook.on_step(step)
    assert fake_profiler == [("start", str(tmp_path / "b")), ("stop",)]
    fake_profiler.clear()
    # ...but a resume already PAST it never starts a partial trace.
    hook = obs.ProfilerHook(str(tmp_path / "c"), start_step=3, num_steps=3)
    for step in (7, 8, 9):
        hook.on_step(step)
    assert fake_profiler == []


def test_profiler_hook_stop_idempotent_and_guarding(tmp_path, fake_profiler):
    hook = obs.ProfilerHook(str(tmp_path), start_step=1, num_steps=1)
    hook.stop()  # never started: no-op
    assert fake_profiler == []
    with obs.ProfilerHook(str(tmp_path), start_step=1, num_steps=5) as h:
        h.on_step(0)  # opens at start_step - 1
        assert fake_profiler[-1][0] == "start"
    # context exit force-stops a still-open window
    assert fake_profiler[-1] == ("stop",)
    with pytest.raises(ValueError):
        obs.ProfilerHook(str(tmp_path), start_step=0)
    with pytest.raises(ValueError):
        obs.ProfilerHook(str(tmp_path), num_steps=0)


def test_profiler_from_env(tmp_path, monkeypatch):
    assert obs.profiler_from_env() is None
    monkeypatch.setenv("KATATPU_OBS_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("KATATPU_OBS_PROFILE_START", "3")
    monkeypatch.setenv("KATATPU_OBS_PROFILE_STEPS", "2")
    hook = obs.profiler_from_env()
    assert hook.profile_dir == str(tmp_path)
    assert hook.start_step == 3 and hook.stop_after == 4


def test_fit_drives_profiler(tmp_path, fake_profiler, sink):
    from kata_xpu_device_plugin_tpu.models import llama3_train_test
    from kata_xpu_device_plugin_tpu.parallel import (
        build_mesh,
        fit,
        make_loader,
        make_train_step,
    )

    cfg = llama3_train_test()
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    init_state, step = make_train_step(cfg, mesh)
    loader = make_loader(
        np.arange(4096, dtype=np.int32) % cfg.vocab_size,
        batch=8, seq_len=31, mesh=mesh, seed=3,
    )
    hook = obs.ProfilerHook(str(tmp_path / "p"), start_step=2, num_steps=1)
    fit(init_state, step, loader, steps=3, key=jax.random.PRNGKey(1),
        profiler=hook)
    assert fake_profiler == [("start", str(tmp_path / "p")), ("stop",)]


# ----- JSONL sink thread safety (ISSUE 11 satellite) -------------------------


def test_event_sink_concurrent_emits_no_interleaving(tmp_path):
    """Concurrent emitters through ONE sink — the overlap scheduler, the
    recovery supervisor, and a drain signal path all share the process
    default — must produce a parseable stream: every line one complete
    JSON object, nothing interleaved or torn, nothing lost."""
    import threading

    path = str(tmp_path / "concurrent.jsonl")
    n_threads, n_each = 8, 200
    with obs.EventSink(path) as s:
        barrier = threading.Barrier(n_threads)

        def pound(tid):
            barrier.wait()  # maximal contention: all start together
            for i in range(n_each):
                s.emit(
                    "serving", "stress",
                    thread=tid, i=i,
                    # A long-ish payload widens the torn-write window a
                    # non-atomic writer would expose.
                    pad="x" * 64,
                )

        threads = [
            threading.Thread(target=pound, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    # Parse back STRICTLY (read_events skips bad lines — that leniency
    # would hide exactly the corruption this test exists to catch).
    with open(path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    events = [json.loads(line) for line in lines]  # raises on any tear
    assert len(events) == n_threads * n_each
    seen = {(e["thread"], e["i"]) for e in events}
    assert len(seen) == n_threads * n_each  # none lost, none duplicated


# ----- event-schema drift gate (ISSUE 11 satellite) --------------------------


def _emitted_event_names():
    """Every event NAME the package can emit, collected statically:
    literal second arguments of ``*.emit(kind, name, ...)`` calls (kind
    ``"span"`` excluded — span names are the span catalog, not events),
    literal first arguments of the serving ``self._emit(name, ...)``
    wrapper, and literal ``event=`` keywords (the env-knob degrade
    events routed through ``resilience.env_int``/``env_float``)."""
    import ast
    import pathlib

    import kata_xpu_device_plugin_tpu

    pkg_root = pathlib.Path(kata_xpu_device_plugin_tpu.__file__).parent
    names: set[str] = set()
    for p in pkg_root.rglob("*.py"):
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            attr = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None
            )
            args = node.args
            if attr == "emit":
                if (
                    len(args) >= 2
                    and all(
                        isinstance(a, ast.Constant)
                        and isinstance(a.value, str)
                        for a in args[:2]
                    )
                    and args[0].value != "span"
                ):
                    names.add(args[1].value)
            elif attr == "_emit":
                if (
                    args
                    and isinstance(args[0], ast.Constant)
                    and isinstance(args[0].value, str)
                ):
                    names.add(args[0].value)
            for kw in node.keywords:
                if (
                    kw.arg == "event"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    names.add(kw.value.value)
    return names


def test_every_emitted_event_name_is_documented():
    """The event-schema drift gate (the PR 7 seam-doc pin pattern,
    applied to events): every event name the package can emit must
    appear in docs/observability.md — an event consumers cannot look up
    is telemetry debt. Adding an event means documenting it; this test
    is the tripwire."""
    import pathlib

    import kata_xpu_device_plugin_tpu

    doc = (
        pathlib.Path(kata_xpu_device_plugin_tpu.__file__).parent.parent
        / "docs" / "observability.md"
    ).read_text(encoding="utf-8")
    names = _emitted_event_names()
    assert len(names) >= 30  # the collector found the real surface
    undocumented = sorted(n for n in names if n not in doc)
    assert not undocumented, (
        f"event names emitted but absent from docs/observability.md: "
        f"{undocumented} — document them (schema drift gate, ISSUE 11)"
    )


# ----- flight recorder (ISSUE 11) --------------------------------------------


@pytest.fixture
def flight_mod():
    from kata_xpu_device_plugin_tpu.obs import flight

    return flight


def test_flight_ring_armed_with_sink_off(flight_mod):
    """The recorder's whole reason to exist: events are captured even
    when the JSONL sink is disabled — the incident nobody enabled
    KATATPU_OBS for is the one that matters."""
    rec = flight_mod.FlightRecorder(capacity=16)
    prev_rec = flight_mod.set_default_recorder(rec)
    prev_sink = obs.set_default_sink(None)
    try:
        assert obs.emit("serving", "ttft", rid=1) is None  # sink off
        assert obs.emit("serving", "recovery", error="x") is None
    finally:
        obs.set_default_sink(prev_sink)
        flight_mod.set_default_recorder(prev_rec)
    names = [e["name"] for e in rec.snapshot()]
    assert names == ["ttft", "recovery"]
    assert all("ts" in e for e in rec.snapshot())


def test_flight_ring_bounded(flight_mod):
    rec = flight_mod.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record({"kind": "serving", "name": "tick", "i": i})
    snap = rec.snapshot()
    assert len(snap) == 4
    assert [e["i"] for e in snap] == [6, 7, 8, 9]  # most recent survive


def test_flight_dump_on_terminal_event(flight_mod, tmp_path, monkeypatch):
    monkeypatch.setenv(flight_mod.ENV_DIR, str(tmp_path / "dumps"))
    rec = flight_mod.FlightRecorder(capacity=8)
    prev = flight_mod.set_default_recorder(rec)
    try:
        obs.emit("serving", "ttft", rid=0, trace="feedface")
        obs.emit(
            "serving", "chip_loss_fatal",
            server="s1", trace="feedface", why="single_chip",
        )
    finally:
        flight_mod.set_default_recorder(prev)
    assert len(rec.dumps) == 1
    dump = obs.read_events(rec.dumps[0])
    assert dump[-1]["name"] == "chip_loss_fatal"
    # The postmortem joins: the fatal event AND the preceding context
    # carry the trace id.
    assert dump[-1]["trace"] == "feedface"
    assert dump[0]["name"] == "ttft"


def test_flight_clean_stream_never_dumps(flight_mod):
    rec = flight_mod.FlightRecorder(capacity=8)
    for name in ("ttft", "checkpoint", "recovery", "kv_preempt"):
        rec.record({"kind": "serving", "name": name})
    # A CLEAN drain (failed == 0) is not an incident.
    rec.record({"kind": "serving", "name": "drain", "failed": 0})
    assert rec.dumps == []


def test_flight_failed_drain_dumps(flight_mod, tmp_path, monkeypatch):
    monkeypatch.setenv(flight_mod.ENV_DIR, str(tmp_path / "dumps"))
    rec = flight_mod.FlightRecorder(capacity=8)
    rec.record({"kind": "serving", "name": "drain", "failed": 3})
    assert len(rec.dumps) == 1


def test_flight_kill_switch_and_capacity_env(flight_mod, monkeypatch):
    monkeypatch.setenv(flight_mod.ENV_ENABLE, "0")
    assert flight_mod.configure_from_env(force=True) is None
    # Emitting with the recorder disarmed (and sink off) is a no-op.
    prev_sink = obs.set_default_sink(None)
    try:
        assert obs.emit("serving", "chip_loss_fatal", server="x") is None
    finally:
        obs.set_default_sink(prev_sink)
    monkeypatch.delenv(flight_mod.ENV_ENABLE)
    monkeypatch.setenv(flight_mod.ENV_RING, "7")
    rec = flight_mod.configure_from_env(force=True)
    assert rec is not None and rec.capacity == 7
    monkeypatch.delenv(flight_mod.ENV_RING)
    flight_mod.configure_from_env(force=True)


def test_flight_records_span_events(flight_mod):
    """Spans flow through events.emit, so the ring holds them too — the
    postmortem's timeline is spans AND events, like the JSONL stream."""
    rec = flight_mod.FlightRecorder(capacity=8)
    prev = flight_mod.set_default_recorder(rec)
    prev_sink = obs.set_default_sink(None)
    try:
        with obs.span("plugin.Allocate", resource="google.com/tpu"):
            pass
    finally:
        obs.set_default_sink(prev_sink)
        flight_mod.set_default_recorder(prev)
    snap = rec.snapshot()
    assert len(snap) == 1 and snap[0]["kind"] == "span"
    assert snap[0]["name"] == "plugin.Allocate" and snap[0]["trace"]
