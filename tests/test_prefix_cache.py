"""Shared-prefix KV cache (guest/prefix_cache.py + suffix-only prefill).

Oracle, as everywhere in serving: the prefix store is a SCHEDULING/reuse
optimization — greedy tokens must equal the cold server (and therefore the
per-request ``generate()`` oracle) for every composition, while the radix
index, refcounts, and LRU eviction obey their documented semantics.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.prefix_cache import (
    PrefixStore,
    RadixIndex,
    _FreeList,
)
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import (
    init_params,
    prefill,
    prefill_suffix,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _shared_prefix_prompts(cfg, n, prefix_len=10, tails=(2, 3, 4), seed=1):
    key = jax.random.PRNGKey(seed)
    shared = np.asarray(
        jax.random.randint(key, (prefix_len,), 0, cfg.vocab_size), np.int32
    )
    out = []
    for i in range(n):
        tail = np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (tails[i % len(tails)],), 0,
            cfg.vocab_size,
        ), np.int32)
        out.append(np.concatenate([shared, tail]))
    return out


# ----- radix index ---------------------------------------------------------


def test_radix_insert_and_longest_match():
    idx = RadixIndex()
    a = np.array([1, 2, 3, 4], np.int32)
    idx.insert(a, "A")
    assert idx.longest_match(a) == (4, "A")
    # Longer query still matches the registered depth.
    assert idx.longest_match(np.array([1, 2, 3, 4, 9], np.int32)) == (4, "A")
    # Prefix of the entry (mid-edge) matches nothing.
    assert idx.longest_match(np.array([1, 2, 3], np.int32)) == (0, None)
    # Divergence before the entry depth matches nothing.
    assert idx.longest_match(np.array([1, 2, 9, 4], np.int32)) == (0, None)
    assert idx.longest_match(np.array([7], np.int32)) == (0, None)


def test_radix_edge_split_and_nesting():
    idx = RadixIndex()
    idx.insert(np.array([1, 2, 3, 4], np.int32), "long")
    # Inserting a strict prefix splits the compressed edge.
    idx.insert(np.array([1, 2], np.int32), "short")
    assert idx.longest_match(np.array([1, 2, 3, 4], np.int32)) == (4, "long")
    assert idx.longest_match(np.array([1, 2, 3], np.int32)) == (2, "short")
    assert idx.longest_match(np.array([1, 2, 9], np.int32)) == (2, "short")
    # A diverging branch below the split point.
    idx.insert(np.array([1, 2, 7, 7], np.int32), "branch")
    assert idx.longest_match(np.array([1, 2, 7, 7, 1], np.int32)) == (4, "branch")
    assert idx.longest_match(np.array([1, 2, 3, 4], np.int32)) == (4, "long")
    assert len(idx) == 3


def test_radix_remove_prunes():
    idx = RadixIndex()
    n1 = idx.insert(np.array([1, 2, 3, 4], np.int32), "A")
    n2 = idx.insert(np.array([1, 2], np.int32), "B")
    idx.remove(n1)
    assert idx.longest_match(np.array([1, 2, 3, 4], np.int32)) == (2, "B")
    idx.remove(n2)
    assert idx.longest_match(np.array([1, 2, 3, 4], np.int32)) == (0, None)
    assert len(idx) == 0


def test_freelist_coalesces():
    fl = _FreeList(16)
    a = fl.alloc(8)
    b = fl.alloc(8)
    assert {a, b} == {0, 8} and fl.alloc(1) is None
    fl.free(a, 8)
    fl.free(b, 8)
    assert fl.alloc(16) == 0  # neighbors merged back into one range


# ----- store semantics -----------------------------------------------------


def _store_with(cfg, params, prompts, capacity, buckets):
    store = PrefixStore(cfg, capacity, buckets)
    for p in prompts:
        caches, _, _ = prefill(
            params, jnp.asarray(p)[None, :], cfg, 32, return_logits=True
        )
        store.insert(p, caches, 0)
    return store


def test_store_bucket_aligned_boundaries(model):
    cfg, params = model
    p = np.arange(1, 14, dtype=np.int32)  # 13 tokens
    store = _store_with(cfg, params, [p], capacity=32, buckets=(4, 8, 16))
    # Insert bound: largest bucket <= len - 1 = 12 → 8; entries at 4 and 8.
    assert store.tokens_used == 8
    hit = store.lookup(p)
    assert hit is not None and hit.length == 8
    store.release(hit)
    # A prompt diverging after 5 tokens still matches the 4-boundary.
    q = np.concatenate([p[:5], np.array([99, 98, 97], np.int32)])
    hq = store.lookup(q)
    assert hq is not None and hq.length == 4
    store.release(hq)
    # The match is capped at len(prompt) - 1: an 8-token prompt equal to
    # the cached prefix must match at 4, leaving >= 1 suffix token.
    h8 = store.lookup(p[:8])
    assert h8 is not None and h8.length == 4
    store.release(h8)
    # Shorter than every bucket: no match, counted as a miss.
    assert store.lookup(p[:3]) is None
    assert store.misses == 1


def test_store_refcount_blocks_eviction_and_lru_order(model):
    cfg, params = model
    p1 = np.arange(0, 10, dtype=np.int32)
    p2 = np.arange(50, 60, dtype=np.int32)
    store = _store_with(cfg, params, [p1, p2], capacity=16, buckets=(8,))
    assert store.tokens_used == 16  # full
    h1 = store.lookup(p1)  # pins p1's segment AND makes it most-recent
    assert h1 is not None

    def insert(p):
        caches, _, _ = prefill(
            params, jnp.asarray(p)[None, :], cfg, 32, return_logits=True
        )
        return store.insert(p, caches, 0)

    # Eviction under capacity pressure while a referencing request is in
    # flight: p2 (unreferenced) must be the victim, never pinned p1.
    assert insert(np.arange(100, 110, dtype=np.int32))
    assert store.lookup(p2) is None  # evicted
    h1b = store.lookup(p1)
    assert h1b is not None  # survived while referenced
    assert store.evictions == 1
    # Everything pinned → insertion skips instead of evicting.
    h3 = store.lookup(np.arange(100, 110, dtype=np.int32))
    assert h3 is not None
    assert not insert(np.arange(200, 210, dtype=np.int32))
    assert store.insert_skips == 1
    for h in (h1, h1b, h3):
        store.release(h)
    # Unpinned again: LRU now evictable, insert succeeds.
    assert insert(np.arange(200, 210, dtype=np.int32))
    assert store.evictions == 2


def test_store_lru_prefers_least_recent(model):
    cfg, params = model
    p1 = np.arange(0, 10, dtype=np.int32)
    p2 = np.arange(50, 60, dtype=np.int32)
    store = _store_with(cfg, params, [p1, p2], capacity=16, buckets=(8,))
    # Touch p1 (lookup/release) so p2 becomes least-recently-used.
    store.release(store.lookup(p1))
    caches, _, _ = prefill(
        params, jnp.asarray(np.arange(100, 110, dtype=np.int32))[None, :],
        cfg, 32, return_logits=True,
    )
    store.insert(np.arange(100, 110, dtype=np.int32), caches, 0)
    assert store.lookup(p2) is None  # LRU victim
    h = store.lookup(p1)
    assert h is not None  # recently-used survivor
    store.release(h)


def test_store_eviction_emits_event(model, tmp_path):
    from kata_xpu_device_plugin_tpu import obs

    cfg, params = model
    sink = obs.EventSink(str(tmp_path / "events.jsonl"))
    prev = obs.set_default_sink(sink)
    try:
        p1 = np.arange(0, 10, dtype=np.int32)
        store = _store_with(cfg, params, [p1], capacity=8, buckets=(8,))
        caches, _, _ = prefill(
            params, jnp.asarray(np.arange(60, 70, dtype=np.int32))[None, :],
            cfg, 32, return_logits=True,
        )
        store.insert(np.arange(60, 70, dtype=np.int32), caches, 0)
    finally:
        obs.set_default_sink(prev)
        sink.close()
    evicts = [
        ev for ev in obs.read_events(str(tmp_path / "events.jsonl"))
        if ev.get("name") == "prefix_evict"
    ]
    assert len(evicts) == 1 and evicts[0]["tokens"] == 8


def test_store_validation(model):
    cfg, _params = model
    with pytest.raises(ValueError, match="buckets"):
        PrefixStore(cfg, 64, ())
    with pytest.raises(ValueError, match="capacity"):
        PrefixStore(cfg, 4, (8,))


# ----- suffix prefill numerics --------------------------------------------


@pytest.mark.parametrize("kv_quant", [False, True])
def test_prefill_suffix_matches_cold_prefill(model, kv_quant):
    """Cold full-length prefill vs copy-prefix + suffix-only prefill: the
    caches agree on every real row and the boundary logits agree — the
    greedy continuation is therefore identical (the server-level tests
    lock the full token streams)."""
    cfg, params = model
    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(3), (12,), 0, cfg.vocab_size
    ), np.int32)
    m, max_len = 8, 24
    cold_caches, cold_logits, cold_pos = prefill(
        params, jnp.asarray(prompt)[None, :], cfg, max_len,
        return_logits=True, kv_quantized=kv_quant,
    )
    # Store the prefix, gather it back, prefill only the suffix.
    store = PrefixStore(cfg, 16, (m,), kv_quant=kv_quant)
    store.insert(prompt, cold_caches, 0)
    hit = store.lookup(prompt)
    assert hit is not None and hit.length == m
    caches = store.materialize(hit, max_len)
    sfx_caches, sfx_logits, sfx_pos = prefill_suffix(
        params, jnp.asarray(prompt[m:])[None, :], cfg, caches,
        jnp.int32(m), return_logits=True,
    )
    store.release(hit)
    assert int(sfx_pos) == int(cold_pos) == len(prompt)
    if kv_quant:
        # int8 arenas: the suffix forward reads the QUANTIZED prefix back
        # (exactly what decode does), while the cold prefill attended to
        # the pre-quantization k/v — logits agree to quantization noise,
        # and the greedy stream identity is locked by the server tests.
        np.testing.assert_allclose(
            np.asarray(sfx_logits), np.asarray(cold_logits),
            rtol=0.1, atol=0.5,
        )
        for cold, sfx in zip(
            jax.tree_util.tree_leaves(cold_caches),
            jax.tree_util.tree_leaves(sfx_caches),
        ):
            # Prefix rows are copied VERBATIM — bit-identical int8/scales.
            np.testing.assert_array_equal(
                np.asarray(cold[:, :, :m]), np.asarray(sfx[:, :, :m])
            )
    else:
        np.testing.assert_allclose(
            np.asarray(sfx_logits), np.asarray(cold_logits), rtol=2e-5,
            atol=2e-5,
        )
        for cold, sfx in zip(
            jax.tree_util.tree_leaves(cold_caches),
            jax.tree_util.tree_leaves(sfx_caches),
        ):
            np.testing.assert_allclose(
                np.asarray(cold[:, :, : len(prompt)]),
                np.asarray(sfx[:, :, : len(prompt)]),
                rtol=2e-5, atol=2e-5,
            )
    assert (
        np.asarray(jnp.argmax(sfx_logits, -1))
        == np.asarray(jnp.argmax(cold_logits, -1))
    ).all()


def test_prefill_suffix_padded_true_len_matches_exact(model):
    cfg, params = model
    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(5), (11,), 0, cfg.vocab_size
    ), np.int32)
    m, max_len = 8, 24
    cold_caches, _, _ = prefill(
        params, jnp.asarray(prompt)[None, :], cfg, max_len, return_logits=True
    )
    store = PrefixStore(cfg, 16, (8,))
    store.insert(prompt, cold_caches, 0)
    hit = store.lookup(prompt)
    exact_c, exact_l, exact_p = prefill_suffix(
        params, jnp.asarray(prompt[m:])[None, :], cfg,
        store.materialize(hit, max_len), jnp.int32(m), return_logits=True,
    )
    padded = np.pad(prompt[m:], (0, 5))  # right-pad the suffix
    pad_c, pad_l, pad_p = prefill_suffix(
        params, jnp.asarray(padded)[None, :], cfg,
        store.materialize(hit, max_len), jnp.int32(m), return_logits=True,
        true_len=jnp.int32(len(prompt) - m),
    )
    store.release(hit)
    assert int(pad_p) == int(exact_p) == len(prompt)
    # Padded vs exact run different executables (different shapes tile
    # their reductions differently) — value-identical math, last-ulp fp.
    np.testing.assert_allclose(np.asarray(pad_l), np.asarray(exact_l),
                               rtol=1e-5, atol=1e-5)
    assert (
        np.asarray(jnp.argmax(pad_l, -1)) == np.asarray(jnp.argmax(exact_l, -1))
    ).all()
    for e, p in zip(jax.tree_util.tree_leaves(exact_c),
                    jax.tree_util.tree_leaves(pad_c)):
        np.testing.assert_allclose(
            np.asarray(e[:, :, : len(prompt)]),
            np.asarray(p[:, :, : len(prompt)]), rtol=1e-5, atol=1e-5,
        )


# ----- server integration --------------------------------------------------


def _serve(params, cfg, prompts, budgets=8, **kw):
    srv = GenerationServer(params, cfg, **kw)
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    res = srv.run()
    return [res[r] for r in rids], srv


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("overlap", [True, False])
def test_prefix_serving_greedy_identical_to_cold(model, kv_quant, overlap):
    """The acceptance-criteria oracle: greedy outputs bit-identical between
    the prefix-hit path and the cold path, over bucketed shared-prefix
    prompts, for bf16/fp32 AND int8 (kv_quant) arenas, pipelined and
    lock-step."""
    cfg, params = model
    prompts = _shared_prefix_prompts(cfg, 6)
    common = dict(max_batch=2, max_len=48, chunk=4,
                  prefill_buckets=(4, 8, 16), kv_quant=kv_quant,
                  overlap=overlap)
    ref, _ = _serve(params, cfg, prompts, **common)
    out, srv = _serve(params, cfg, prompts, prefix_cache_tokens=64, **common)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["prefix_hits"] >= 4  # everything after the first admission
    assert st["prefix_hit_ratio"] > 0.5
    assert st["prefix_tokens_reused"] == 8 * st["prefix_hits"]


def test_prefix_serving_batched_suffix_admission(model):
    """A burst of same-prefix requests admits through ONE batched suffix
    forward (prefill_batches counts it), token-identical to cold."""
    cfg, params = model
    # 8 requests, 4 slots: first pass misses cold-batched, later passes
    # hit — with equal tails they group into batched suffix forwards.
    prompts = _shared_prefix_prompts(cfg, 8, tails=(3,))
    common = dict(max_batch=4, max_len=48, chunk=4, prefill_buckets=(4, 8))
    ref, _ = _serve(params, cfg, prompts, **common)
    out, srv = _serve(params, cfg, prompts, prefix_cache_tokens=64, **common)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["prefix_hits"] == 4
    assert st["prefill_batches"] >= 2  # cold [4, 8] batch + suffix batch


def test_stats_prefill_batches_counts_multi_request_forwards_only(model):
    """Satellite contract: prefill_batches counts MULTI-request admission
    forwards (cold prefill_batch or batched suffix), never single-request
    admissions — prefills is the per-request count."""
    cfg, params = model
    prompts = _shared_prefix_prompts(cfg, 3, tails=(3,))
    # One slot: every admission is single-request → 0 batches, N prefills.
    _, solo = _serve(params, cfg, prompts, max_batch=1, max_len=48,
                     prefill_buckets=(4, 8), prefix_cache_tokens=64)
    assert solo.stats()["prefills"] == 3
    assert solo.stats()["prefill_batches"] == 0
    # Two slots: the first pass cold-batches 2 rows → exactly 1 increment
    # for 2 requests (per-forward, not per-row).
    _, duo = _serve(params, cfg, prompts[:2], max_batch=2, max_len=48,
                    prefill_buckets=(4, 8))
    assert duo.stats()["prefills"] == 2
    assert duo.stats()["prefill_batches"] == 1


def test_prefix_hit_ratio_present_when_disabled(model):
    """Dashboards need no schema branch: prefix fields exist (and are
    zero) on servers without a store."""
    cfg, params = model
    prompts = _shared_prefix_prompts(cfg, 2)
    _, srv = _serve(params, cfg, prompts, max_batch=2, max_len=48)
    st = srv.stats()
    assert st["prefix_hit_ratio"] == 0.0
    assert st["prefix_hits"] == 0 and st["prefix_misses"] == 0
    assert st["prefix_tokens_reused"] == 0
    assert st["prefix_store_tokens"] == 0
    assert st["prefix_store_occupancy"] == 0.0


def test_ring_kv_falls_back_to_cold_admission():
    """Miss-path fallback for ring_kv=True (explicitly unsupported): the
    store is disabled, serving stays correct, stats report 0.0."""
    from kata_xpu_device_plugin_tpu.models import mistral_test_config

    cfg = tiny_test_config(dtype=jnp.float32)  # noqa: F841 — fixture dtype
    mcfg = mistral_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(4), mcfg, dtype=jnp.float32)
    prompts = _shared_prefix_prompts(mcfg, 4)
    common = dict(max_batch=2, max_len=64, chunk=4, prefill_buckets=(4, 8, 16))
    ref, _ = _serve(params, mcfg, prompts, budgets=10, **common)
    out, srv = _serve(params, mcfg, prompts, budgets=10, ring_kv=True,
                      prefix_cache_tokens=64, **common)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    assert srv.prefix_store is None
    assert srv.stats()["prefix_hit_ratio"] == 0.0


def test_prefix_serving_in_flight_pin_and_release(model):
    """A prefix hit pins its segment for the request's lifetime (eviction
    under capacity pressure must skip it) and releases at finish."""
    cfg, params = model
    prompts = _shared_prefix_prompts(cfg, 3, tails=(3,))
    srv = GenerationServer(params, cfg, max_batch=1, max_len=48, chunk=4,
                           prefill_buckets=(8,), prefix_cache_tokens=8)
    srv.submit(prompts[0], 2)
    srv.run()  # cold: populates the 8-token store to capacity
    store = srv.prefix_store
    assert store.tokens_used == 8
    srv.submit(prompts[1], 30)  # hit: pins the segment
    assert srv.step()  # admission + first chunk; request still in flight
    seg = next(h.segment for h in srv._slot_prefix if h is not None)
    assert seg.refs == 1
    # Capacity pressure while the referencing request is in flight: the
    # pinned segment must not evict — insertion skips instead.
    other = np.asarray(jax.random.randint(
        jax.random.PRNGKey(9), (10,), 0, cfg.vocab_size), np.int32)
    caches, _, _ = prefill(params, jnp.asarray(other)[None, :], cfg, 48,
                           return_logits=True)
    assert not store.insert(other, caches, 0)
    assert store.insert_skips == 1 and store.evictions == 0
    srv.run()  # drain: finish releases the pin
    assert all(h is None for h in srv._slot_prefix)
    assert seg.refs == 0


def test_prefix_store_deepens_on_hit(model):
    """A hit whose prompt extends past the matched boundary re-inserts
    from its completed slot caches, so an early SHORT prompt cannot
    permanently cap reuse for its lineage at a small bucket."""
    cfg, params = model
    key = jax.random.PRNGKey(17)
    shared = np.asarray(
        jax.random.randint(key, (20,), 0, cfg.vocab_size), np.int32
    )
    tails = [np.asarray(jax.random.randint(
        jax.random.fold_in(key, i), (3,), 0, cfg.vocab_size), np.int32)
        for i in range(3)]
    prompts = [shared[:10],                      # short: caps insert at 8
               np.concatenate([shared, tails[0]]),  # hits 8, deepens to 16
               np.concatenate([shared, tails[1]])]  # must now hit at 16
    common = dict(max_batch=1, max_len=48, chunk=4, prefill_buckets=(4, 8, 16))
    ref, _ = _serve(params, cfg, prompts, **common)
    out, srv = _serve(params, cfg, prompts, prefix_cache_tokens=64, **common)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["prefix_hits"] == 2
    assert st["prefix_tokens_reused"] == 8 + 16  # the deepened boundary hit
    assert srv.prefix_store.tokens_used == 8 + 16  # short + deepened segments


def test_degraded_suffix_shape_falls_back_to_cold(model):
    """A hit whose suffix fits no bucket inside the arena — while the
    whole prompt does — is cancelled in favor of cold bucketed admission
    (the executable-count bound wins), with store counters reflecting it."""
    cfg, params = model
    key = jax.random.PRNGKey(23)
    shared = np.asarray(
        jax.random.randint(key, (21,), 0, cfg.vocab_size), np.int32
    )
    # buckets (8, 21), max_len 28: the 21-token prompt hits at 8, its
    # 13-token suffix needs bucket 21 but 8 + 21 > 28 → degraded.
    common = dict(max_batch=1, max_len=28, chunk=4, prefill_buckets=(8, 21))
    prompts = [shared[:10], shared]
    ref, _ = _serve(params, cfg, prompts, budgets=6, **common)
    out, srv = _serve(params, cfg, prompts, budgets=6,
                      prefix_cache_tokens=64, **common)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["prefix_hits"] == 0 and st["prefix_misses"] == 2
    store_st = srv.prefix_store.stats()
    assert store_st["hits"] == 0 and store_st["misses"] == 2  # cancel() undid it


def test_store_unlookup_leaves_no_trace(model):
    """unlookup() (the paged head-of-line retry primitive, same contract
    as PagedPrefixTier.unlookup) reverses a lookup wholesale: unlike
    cancel(), no miss sticks, and the pin is released."""
    cfg, params = model
    p = np.arange(1, 14, dtype=np.int32)
    store = _store_with(cfg, params, [p], capacity=32, buckets=(4, 8, 16))
    assert store.lookup(np.arange(50, 60, dtype=np.int32)) is None
    store.unlookup(None)
    assert (store.hits, store.misses) == (0, 0)
    hit = store.lookup(p)
    assert hit is not None
    store.unlookup(hit)
    assert (store.hits, store.misses, store.tokens_reused) == (0, 0, 0)
    assert hit.segment.refs == 0


def test_shared_store_across_servers(model):
    """One PrefixStore backing two servers: the second server's first
    request hits a prefix the first server deposited."""
    cfg, params = model
    prompts = _shared_prefix_prompts(cfg, 3, tails=(3,))
    store = PrefixStore(cfg, 64, (4, 8, 16))
    ref, _ = _serve(params, cfg, prompts, max_batch=2, max_len=48,
                    prefill_buckets=(4, 8, 16))
    _, srv1 = _serve(params, cfg, prompts[:1], max_batch=2, max_len=48,
                     prefill_buckets=(4, 8, 16), prefix_store=store)
    out2, srv2 = _serve(params, cfg, prompts[1:], max_batch=2, max_len=48,
                        prefill_buckets=(4, 8, 16), prefix_store=store)
    for r, o in zip(ref[1:], out2):
        np.testing.assert_array_equal(o, r)
    assert srv1.stats()["prefix_hits"] == 0
    assert srv2.stats()["prefix_hits"] == 2  # warm from server 1's insert
    assert srv2.stats()["prefix_hit_ratio"] == 1.0


def test_prefix_server_validation(model):
    cfg, params = model
    with pytest.raises(ValueError, match="prefill_buckets"):
        GenerationServer(params, cfg, max_len=32, prefix_cache_tokens=64)
    store = PrefixStore(cfg, 64, (4, 8))
    with pytest.raises(ValueError, match="prefix_store"):
        GenerationServer(params, cfg, max_len=32, prefill_buckets=(4, 16),
                         prefix_store=store)  # bucket mismatch
    with pytest.raises(ValueError, match="prefix_store"):
        GenerationServer(params, cfg, max_len=32, prefill_buckets=(4, 8),
                         kv_quant=True, prefix_store=store)  # dtype mismatch


def test_prefix_env_default(model, monkeypatch):
    """KATA_TPU_PREFIX_CACHE_TOKENS (the env the daemon's
    --prefix-cache-tokens knob injects into AllocateResponse) sizes the
    store when the caller passes nothing; an explicit 0 overrides it; and
    on a server WITHOUT prefill_buckets the node-wide env must degrade
    (store disabled) instead of crashing a previously-valid server —
    only an explicit prefix_cache_tokens= argument raises."""
    cfg, params = model
    monkeypatch.setenv("KATA_TPU_PREFIX_CACHE_TOKENS", "32")
    srv = GenerationServer(params, cfg, max_len=32, prefill_buckets=(4, 8))
    assert srv.prefix_store is not None
    assert srv.prefix_store.capacity_tokens == 32
    off = GenerationServer(params, cfg, max_len=32, prefill_buckets=(4, 8),
                           prefix_cache_tokens=0)
    assert off.prefix_store is None
    no_buckets = GenerationServer(params, cfg, max_len=32)  # env-only: degrade
    assert no_buckets.prefix_store is None
    assert no_buckets.stats()["prefix_hit_ratio"] == 0.0
    with pytest.raises(ValueError, match="prefill_buckets"):
        GenerationServer(params, cfg, max_len=32, prefix_cache_tokens=32)
    # A malformed node-wide env degrades too — it must never crash guests.
    monkeypatch.setenv("KATA_TPU_PREFIX_CACHE_TOKENS", "16k")
    bad = GenerationServer(params, cfg, max_len=32, prefill_buckets=(4, 8))
    assert bad.prefix_store is None


def test_store_repairs_lost_shallow_boundary(model):
    """Eviction of a shallow segment whose boundary a deeper overlapping
    segment also covers: the next insert of the lineage re-registers the
    shallow boundary against the surviving segment (whose rows contain
    exactly those tokens), so reuse does not silently degrade forever."""
    cfg, params = model
    lineage = np.arange(1, 13, dtype=np.int32)  # 12 tokens

    def mkcache(p):
        c, _, _ = prefill(params, jnp.asarray(p)[None, :], cfg, 32,
                          return_logits=True)
        return c

    store = PrefixStore(cfg, 12, (4, 8))
    store.insert(lineage[:6], mkcache(lineage[:6]), 0)   # A: 4 tokens, entry@4
    store.insert(lineage, mkcache(lineage), 0)           # B: 8 tokens, entry@8
    assert store.tokens_used == 12
    # Pressure from an unrelated lineage (needing one 4-token slot)
    # evicts A (LRU) — the depth-4 entry dies with it even though B's
    # rows still cover [0, 4).
    other = np.arange(50, 55, dtype=np.int32)
    store.insert(other, mkcache(other), 0)
    assert store.evictions == 1
    assert store.lookup(lineage[:6]) is None  # the hole
    # The next full-lineage insert repairs it against B instead of
    # storing anything new.
    assert not store.insert(lineage, mkcache(lineage), 0)
    h = store.lookup(lineage[:6])
    assert h is not None and h.length == 4
    h8 = store.lookup(lineage)
    assert h8 is not None and h8.length == 8
    assert h.segment is h8.segment  # the shallow entry points into B
    store.release(h)
    store.release(h8)


def test_allocator_injects_prefix_cache_env():
    """Daemon side of the same knob: config.prefix_cache_tokens rides the
    TPU AllocateResponse env (plugin/allocators.py), mirroring
    compile_cache_dir's delivery path. Host-only — no jax."""
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.discovery.tpu import TpuChip, TpuInventory
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator
    from kata_xpu_device_plugin_tpu.topology.slice import HostTopology

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive,
        prefix_cache_tokens=8192,
    ).allocate(["0"])
    assert wired.envs[C.ENV_PREFIX_CACHE_TOKENS] == "8192"
    bare = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive
    ).allocate(["0"])
    assert C.ENV_PREFIX_CACHE_TOKENS not in bare.envs
