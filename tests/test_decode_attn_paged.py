"""Paged-native split-K decode attention (ISSUE 12).

Three layers of coverage, all CPU interpret mode:

- KERNEL ORACLE: ``pallas_paged_decode_attention`` against the dense
  gather + ``reference_attention`` oracle across ragged lengths and
  boundary blocks (pos at a block edge, pos 0, a partial last block,
  unmapped-ZERO table tails), and the int8 fused-dequant bit-match
  against dequantize-then-attend.
- TP COMPOSITION: the ``make_decode_attn_fn`` shard_map wrapper on the
  forced-8-device host — tp=2 (KV heads shard) and tp=4 (kv-replicated
  layout) identical to tp=1.
- SERVING MATRIX: the existing bit-identity matrix re-run with the
  kernel selected (``decode_attn="pallas_paged"``) — paged × slotted ×
  int8/bf16 × prefix-hit × preemption — greedy tokens equal to the
  ``xla_reference`` path's, plus the backend observability contract
  (once-per-server event, always-present stats field, raise-vs-degrade
  knob semantics).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params
from kata_xpu_device_plugin_tpu.ops.attention import (
    BACKEND_PAGED,
    BACKEND_REFERENCE,
    dense_decode_tile,
    make_decode_attn_fn,
    reference_attention,
)
from kata_xpu_device_plugin_tpu.ops.decode_attn import (
    pallas_paged_decode_attention,
    supports_paged_decode,
)
from kata_xpu_device_plugin_tpu.ops.quant import (
    dequantize_kv,
    quantize_kv,
)


# ----- kernel-level oracle ---------------------------------------------------


def _pool_case(seed=0, B=3, H=8, KV=2, D=16, bs=4, NB=6, paged_len=22,
               dtype=jnp.float32):
    """A pool + tables + ragged positions covering the boundary cases:
    pos at a block edge (bs*3-1), pos 0, pos in the partial last block
    (paged_len-1 with paged_len % bs != 0), unmapped tails at ZERO."""
    num_blocks = 2 + B * NB
    NT = num_blocks * bs
    key = jax.random.PRNGKey(seed)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, 1, H, D), dtype)
    pool_k = jax.random.normal(kk, (1, NT, KV, D), dtype)
    pool_v = jax.random.normal(kv_, (1, NT, KV, D), dtype)
    pool_k = pool_k.at[0, :bs].set(0.0)  # the ZERO block really is zero
    pool_v = pool_v.at[0, :bs].set(0.0)
    pos = jnp.asarray([0, bs * 3 - 1, paged_len - 1][:B], jnp.int32)
    tables = np.zeros((B, NB), np.int32)  # unmapped tail = ZERO block
    for b in range(B):
        for j in range(int(pos[b]) // bs + 1):
            tables[b, j] = 2 + b * NB + j
    return q, pool_k, pool_v, jnp.asarray(tables), pos, bs, paged_len


def _oracle(q, pool_k, pool_v, tables, pos, bs, paged_len):
    """The gather path the transformer's paged branch runs: dense view
    through the tables, then the XLA reference with ragged q_offset."""
    B = q.shape[0]
    idx = (tables * bs)[:, :, None] + jnp.arange(bs)[None, None, :]
    idx = idx.reshape(B, -1)[:, :paged_len]
    return reference_attention(
        q, pool_k[0][idx], pool_v[0][idx], causal=True, q_offset=pos,
    )


def test_paged_kernel_matches_reference_ragged_boundaries():
    q, pk, pv, tables, pos, bs, plen = _pool_case()
    out = pallas_paged_decode_attention(
        q, pk, pv, tables, pos, block_size=bs, paged_len=plen,
        interpret=True,
    )
    ref = _oracle(q, pk, pv, tables, pos, bs, plen)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_paged_kernel_unmapped_rows_read_zero():
    # A lane whose table is ALL zero-block entries (a dead lane after the
    # SCRATCH→ZERO remap) must attend pure zeros — same output the dense
    # path computes from a fresh arena, finite (no NaN from the empty-
    # softmax denominator guard).
    q, pk, pv, tables, pos, bs, plen = _pool_case()
    tables = tables.at[0].set(0)
    out = pallas_paged_decode_attention(
        q, pk, pv, tables, pos, block_size=bs, paged_len=plen,
        interpret=True,
    )
    ref = _oracle(q, pk, pv, tables, pos, bs, plen)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_paged_kernel_dead_lane_stale_pos_clamps():
    # Dead lanes carry stale, ever-growing positions (the serving scan
    # advances every lane); the index map must clamp inside the table
    # and the output stay finite (it is discarded, never read).
    q, pk, pv, tables, pos, bs, plen = _pool_case()
    pos = pos.at[0].set(10_000)
    out = pallas_paged_decode_attention(
        q, pk, pv, tables, pos, block_size=bs, paged_len=plen,
        interpret=True,
    )
    assert np.isfinite(np.asarray(out)).all()


def test_paged_kernel_int8_dequant_bitmatch():
    """The fused in-kernel dequant is VALUE-IDENTICAL to dequantize-then-
    attend: same int8→fp32 cast, same fp32 scale multiply, same cast to
    the activation dtype — so the two orderings are bit-equal."""
    q, pk, pv, tables, pos, bs, plen = _pool_case()
    qt_k, qt_v = quantize_kv(pk), quantize_kv(pv)
    fused = pallas_paged_decode_attention(
        q, qt_k, qt_v, tables, pos, block_size=bs, paged_len=plen,
        interpret=True,
    )
    deq = pallas_paged_decode_attention(
        q, dequantize_kv(qt_k, q.dtype), dequantize_kv(qt_v, q.dtype),
        tables, pos, block_size=bs, paged_len=plen, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(deq))


def test_supports_paged_decode_contract():
    # Interpret mode (CPU tests) has no tiling constraints.
    assert supports_paged_decode(16, 4, interpret=True)
    assert not supports_paged_decode(16, 0, interpret=True)
    # Hardware: head_dim lane-aligned, tile on the sublane quantum (the
    # kv_arena block-size alignment contract).
    assert supports_paged_decode(128, 16)
    assert supports_paged_decode(64, 8)
    assert not supports_paged_decode(16, 16)   # head_dim unaligned
    assert not supports_paged_decode(128, 12)  # tile off the quantum
    assert not supports_paged_decode(128, 4)   # tile below it


def test_dense_decode_tile_selection():
    assert dense_decode_tile(256) == 128
    assert dense_decode_tile(48) == 16
    assert dense_decode_tile(24) == 8
    assert dense_decode_tile(22) == 0  # no divisor — XLA fallback


# ----- tp composition (shard_map over the forced-8-device host) -------------


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)  # n_kv_heads=2
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("tp", [2, 4])
def test_kernel_shard_map_identity(model, tp, quantized):
    """tp=2: KV heads divide — the pool shards its head axis. tp=4: they
    do not — the kv-replicated layout runs fully replicated inside the
    same wrapper. Both must be bit-identical to the unwrapped kernel."""
    from kata_xpu_device_plugin_tpu.guest.tp_serving import serving_mesh

    cfg, _ = model
    B, bs, NB, plen = 2, 4, 6, 24
    NT = (2 + B * NB) * bs
    key = jax.random.PRNGKey(3)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, 1, cfg.n_heads, cfg.head_dim), jnp.float32)
    pk = jax.random.normal(kk, (1, NT, cfg.n_kv_heads, cfg.head_dim),
                           jnp.float32)
    pv = jax.random.normal(kv_, (1, NT, cfg.n_kv_heads, cfg.head_dim),
                           jnp.float32)
    if quantized:
        pk, pv = quantize_kv(pk), quantize_kv(pv)
    tables = jnp.asarray(
        [[2 + b * NB + j for j in range(NB)] for b in range(B)], jnp.int32
    )
    pos = jnp.asarray([plen - 1, bs * 2], jnp.int32)

    base = make_decode_attn_fn(
        cfg, paged=True, block_size=bs, paged_len=plen,
        quantized=quantized, interpret=True,
    )
    sharded = make_decode_attn_fn(
        cfg, paged=True, block_size=bs, paged_len=plen,
        quantized=quantized, mesh=serving_mesh(tp), tp=tp, interpret=True,
    )
    ref = base(q, pk, pv, tables, pos)
    out = sharded(q, pk, pv, tables, pos)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_make_decode_attn_fn_refuses_unmodeled_masks(model):
    cfg, _ = model
    from dataclasses import replace

    with pytest.raises(ValueError, match="sliding-window"):
        make_decode_attn_fn(
            replace(cfg, sliding_window=8), paged=True, block_size=4,
            paged_len=16, interpret=True,
        )
    with pytest.raises(ValueError, match="softcap"):
        make_decode_attn_fn(
            replace(cfg, attn_logits_softcap=50.0), paged=True,
            block_size=4, paged_len=16, interpret=True,
        )


# ----- serving matrix with the kernel selected ------------------------------


def _prompts(cfg, lengths, seed=1):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


def _serve(params, cfg, prompts, budgets=8, **kw):
    srv = GenerationServer(params, cfg, **kw)
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    res = srv.run()
    return [res[r] for r in rids], srv


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("paged", [True, False])
def test_serving_kernel_greedy_identical_to_reference(model, paged, kv_quant):
    """The acceptance matrix's core: the SAME traffic (mixed lengths,
    queue pressure) through the kernel backend and the XLA gather
    backend, paged and slotted arenas, bf16 and int8 pools — greedy
    outputs bit-identical."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 9, 6, 12, 3, 7])
    common = dict(max_batch=3, max_len=32, chunk=4, kv_quant=kv_quant)
    if paged:
        common.update(kv_pool_tokens=3 * 32 + 16, kv_block_size=8)
    ref, ref_srv = _serve(params, cfg, prompts,
                          decode_attn=BACKEND_REFERENCE, **common)
    out, srv = _serve(params, cfg, prompts, decode_attn=BACKEND_PAGED,
                      **common)
    assert srv.paged == paged
    assert srv.stats()["decode_backend"] == BACKEND_PAGED
    assert ref_srv.stats()["decode_backend"] == BACKEND_REFERENCE
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


def test_serving_kernel_with_prefix_hits(model):
    """Kernel × the shared-prefix tier: hit admissions reference tier
    blocks read-only from their lane tables — the kernel reads them in
    place — and outputs equal the reference backend's."""
    cfg, params = model
    base = np.arange(16, dtype=np.int32)
    prompts = [np.concatenate([base, p]) for p in
               _prompts(cfg, [4, 6, 3, 5, 7, 4], seed=5)]
    common = dict(max_batch=3, max_len=40, chunk=4,
                  prefill_buckets=(8, 16, 24),
                  kv_pool_tokens=3 * 40 + 32, kv_block_size=8,
                  prefix_cache_tokens=1)  # paged: the tier's ENABLE switch
    ref, _ = _serve(params, cfg, prompts, budgets=10,
                    decode_attn=BACKEND_REFERENCE, **common)
    out, srv = _serve(params, cfg, prompts, budgets=10,
                      decode_attn=BACKEND_PAGED, **common)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["decode_backend"] == BACKEND_PAGED
    assert st["prefix_hits"] >= 1          # the tier really was shared


def test_serving_kernel_with_preemption(model, capture_events):
    """Kernel × preemption: a pool barely above one full-length request
    forces spill/requeue/restore mid-decode — outputs equal the
    reference backend's and the preempt/resume machinery engaged."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 9, 6, 12, 3, 7, 5, 8], seed=2)
    common = dict(max_batch=4, max_len=32, chunk=4,
                  kv_pool_tokens=32 + 3 * 8, kv_block_size=8)
    ref, _ = _serve(params, cfg, prompts, budgets=14,
                    decode_attn=BACKEND_REFERENCE, **common)
    (out, srv), events = capture_events(
        lambda: _serve(params, cfg, prompts, budgets=14,
                       decode_attn=BACKEND_PAGED, **common),
    )
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)
    st = srv.stats()
    assert st["decode_backend"] == BACKEND_PAGED
    assert st["preemptions"] >= 1          # the pool really did spill
    names = [e.get("name") for e in events]
    assert "kv_preempt" in names and "kv_resume" in names


@pytest.mark.parametrize("tp", [2, 4])
def test_serving_kernel_tp_identical_to_tp1(model, tp):
    """Kernel × tensor parallelism: tp=2 shards the pool's KV heads
    through the shard_map wrapper, tp=4 runs the kv-replicated layout —
    greedy outputs bit-identical to the kernel at tp=1."""
    cfg, params = model
    prompts = _prompts(cfg, [5, 9, 3], seed=7)
    common = dict(max_batch=2, max_len=32, chunk=4, kv_quant=True,
                  kv_pool_tokens=96, kv_block_size=4,
                  decode_attn=BACKEND_PAGED)
    ref, _ = _serve(params, cfg, prompts, tp=1, **common)
    out, srv = _serve(params, cfg, prompts, tp=tp, **common)
    assert srv.stats()["tp_degree"] == tp
    assert srv.stats()["decode_backend"] == BACKEND_PAGED
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(o, r)


# ----- backend observability + knob contract --------------------------------


def test_decode_attn_backend_event_once_per_server(model, capture_events):
    cfg, params = model
    prompts = _prompts(cfg, [4, 6], seed=9)
    (_, srv), events = capture_events(
        lambda: _serve(params, cfg, prompts, max_batch=2, max_len=32,
                       chunk=4, kv_pool_tokens=96, kv_block_size=4,
                       decode_attn=BACKEND_PAGED),
    )
    backend_evs = [e for e in events
                   if e.get("name") == "decode_attn_backend"]
    assert len(backend_evs) == 1  # once per server, at the first decode
    ev = backend_evs[0]
    assert ev["backend"] == BACKEND_PAGED
    assert ev["reason"] == ""
    assert ev["paged"] is True and ev["block_size"] == 4
    st = srv.stats()
    assert st["decode_backend"] == BACKEND_PAGED
    assert st["decode_backend_reason"] == ""


def test_decode_attn_auto_on_cpu_reports_reason(model, capture_events):
    # Automatic selection off-TPU: the XLA path, reason on the event and
    # in stats — interpret mode must never be the silent default.
    cfg, params = model
    prompts = _prompts(cfg, [4], seed=11)
    (_, srv), events = capture_events(
        lambda: _serve(params, cfg, prompts, max_batch=1, max_len=32,
                       chunk=4),
    )
    st = srv.stats()
    assert st["decode_backend"] == BACKEND_REFERENCE
    assert st["decode_backend_reason"] == "cpu_backend"
    evs = [e for e in events if e.get("name") == "decode_attn_backend"]
    assert len(evs) == 1 and evs[0]["reason"] == "cpu_backend"


def test_decode_attn_knob_contract(model, monkeypatch, capture_events):
    cfg, params = model
    # Explicit unknown backend raises.
    with pytest.raises(ValueError, match="unknown decode_attn"):
        GenerationServer(params, cfg, max_batch=1, max_len=16,
                         decode_attn="magic")
    # Explicit kernel on an incompatible server raises (ring_kv).
    from dataclasses import replace

    ring_cfg = replace(cfg, sliding_window=8)
    ring_params = init_params(jax.random.PRNGKey(1), ring_cfg,
                              dtype=jnp.float32)
    with pytest.raises(ValueError, match="incompatible"):
        GenerationServer(ring_params, ring_cfg, max_batch=1, max_len=16,
                         ring_kv=True, decode_attn=BACKEND_PAGED)
    # Env-injected malformed value degrades with an event; env-injected
    # kernel on an incompatible server degrades with the reason in the
    # backend event instead of raising.
    monkeypatch.setenv("KATA_TPU_DECODE_ATTN", "warp9")
    srv, events = capture_events(
        lambda: GenerationServer(params, cfg, max_batch=1, max_len=16),
    )
    assert any(e.get("name") == "decode_attn_invalid" for e in events)
    assert srv.stats()["decode_backend"] == BACKEND_REFERENCE
    monkeypatch.setenv("KATA_TPU_DECODE_ATTN", BACKEND_PAGED)
    srv2 = GenerationServer(ring_params, ring_cfg, max_batch=1,
                            max_len=16, ring_kv=True)
    assert srv2.stats()["decode_backend"] == BACKEND_REFERENCE
    assert srv2.stats()["decode_backend_reason"] == "ring_kv"


def test_decode_attn_speculative_keeps_reference(model):
    # Speculative verification decodes k+1-token spans — the kernel is
    # single-token, so spec servers stay on the XLA path with the reason
    # recorded (and the multi-token branch keeps attn_fn).
    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=1, max_len=32,
                           speculative_k=2, spec_opt_in=True)
    st = srv.stats()
    assert st["decode_backend"] == BACKEND_REFERENCE
    assert st["decode_backend_reason"] == "speculative"


def test_export_metrics_backend_gauge(model):
    from prometheus_client import REGISTRY

    cfg, params = model
    srv = GenerationServer(params, cfg, max_batch=1, max_len=16,
                           kv_pool_tokens=64, kv_block_size=4,
                           decode_attn=BACKEND_PAGED)
    label = srv.export_metrics()
    active = REGISTRY.get_sample_value(
        "kata_tpu_serving_decode_attn_backend",
        {"server": label, "backend": BACKEND_PAGED},
    )
    inactive = REGISTRY.get_sample_value(
        "kata_tpu_serving_decode_attn_backend",
        {"server": label, "backend": BACKEND_REFERENCE},
    )
    assert active == 1.0 and inactive == 0.0
