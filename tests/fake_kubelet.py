"""An in-process fake kubelet (SURVEY §4: "a unix-socket gRPC server
implementing Registration and driving ListAndWatch/Allocate against the real
plugin server") — the no-cluster integration seam."""
from __future__ import annotations

import os
import threading
from concurrent import futures

import grpc

from kata_xpu_device_plugin_tpu.plugin.api import deviceplugin_pb2 as pb
from kata_xpu_device_plugin_tpu.plugin.api import glue
from kata_xpu_device_plugin_tpu.plugin.api import podresources_pb2 as prpb


class FakeKubelet(glue.RegistrationServicer, glue.PodResourcesListerServicer):
    """Serves Registration (and optionally pod-resources) on
    ``<socket_dir>/kubelet.sock`` and records what plugins register."""

    def __init__(self, socket_dir: str):
        self.socket_dir = socket_dir
        self.socket_path = os.path.join(socket_dir, "kubelet.sock")
        self.registrations: list[pb.RegisterRequest] = []
        self.registered = threading.Event()
        self.pod_resources = prpb.ListPodResourcesResponse()
        self._server: grpc.Server | None = None

    # Registration service
    def Register(self, request: pb.RegisterRequest, context) -> pb.Empty:
        self.registrations.append(request)
        self.registered.set()
        return pb.Empty()

    # PodResourcesLister service
    def List(self, request, context) -> prpb.ListPodResourcesResponse:
        return self.pod_resources

    def start(self) -> "FakeKubelet":
        os.makedirs(self.socket_dir, exist_ok=True)
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        glue.add_registration_to_server(self, server)
        glue.add_pod_resources_to_server(self, server)
        server.add_insecure_port(f"unix://{self.socket_path}")
        server.start()
        self._server = server
        return self

    def stop(self) -> None:
        if self._server:
            self._server.stop(grace=0.5).wait()
            self._server = None

    def plugin_stub(self, endpoint: str) -> tuple[grpc.Channel, glue.DevicePluginStub]:
        """Dial back into a plugin's socket the way the kubelet does."""
        channel = grpc.insecure_channel(f"unix://{os.path.join(self.socket_dir, endpoint)}")
        grpc.channel_ready_future(channel).result(timeout=5.0)
        return channel, glue.DevicePluginStub(channel)
