"""Chip-loss tolerance end to end (ISSUE 10).

Two halves under test. Guest: a PERMANENT fault (``chip_loss:<device>``
/ ``ici_error`` schedule kinds) makes the recovery supervisor SHRINK the
serving mesh over the survivors (tp=4 → 2 → 1, floored at ``tp_min``),
re-shard params from the host donor copy, rebuild/restore KV state under
the new sharding, and finish the burst with greedy outputs BIT-IDENTICAL
to a fault-free run — tp-invariance (PR 9) makes that assertable on the
forced-8-device CPU host. With no feasible rung (tp=1, kill switch,
``tp_min`` floor) the load fails LOUDLY into ``failures()`` — none
vanish. Daemon: per-chip health flips emit quarantine/readmit events,
and the allocation-state journal reconciles device→group assignments
across a daemon restart against OBSERVED devices — with zero spurious
Unhealthy flaps.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kata_xpu_device_plugin_tpu import obs
from kata_xpu_device_plugin_tpu.guest import resilience, tp_serving
from kata_xpu_device_plugin_tpu.guest.resilience import (
    KINDS,
    ChipLossFault,
    FaultInjector,
    FaultSpec,
    IciFault,
    parse_schedule,
)
from kata_xpu_device_plugin_tpu.guest.serving import GenerationServer
from kata_xpu_device_plugin_tpu.models import tiny_test_config
from kata_xpu_device_plugin_tpu.models.transformer import init_params
from kata_xpu_device_plugin_tpu.topology import (
    HostTopology,
    degraded_fallbacks,
    guest_meshable_counts,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_test_config(dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    return cfg, params


def _prompts(cfg, lengths, seed=2):
    key = jax.random.PRNGKey(seed)
    return [
        np.asarray(
            jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                               cfg.vocab_size),
            np.int32,
        )
        for i, n in enumerate(lengths)
    ]


def _server(params, cfg, injector=None, **kw):
    # Explicit injector / tp_min on every server: the chaos gate replays
    # this suite under an env KATA_TPU_FAULTS schedule, and ambient knobs
    # must not flip a clean baseline into a faulted run.
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("chunk", 4)
    kw.setdefault("recovery_backoff_s", 0.0)
    kw.setdefault("tp_min", 1)
    return GenerationServer(
        params, cfg,
        fault_injector=injector if injector is not None else FaultInjector(),
        **kw,
    )


def _serve(params, cfg, prompts, budgets=8, injector=None, **kw):
    srv = _server(params, cfg, injector=injector, **kw)
    if isinstance(budgets, int):
        budgets = [budgets] * len(prompts)
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    res = srv.run()
    return rids, res, srv


def _capture_events(tmp_path, fn, name="ev.jsonl"):
    sink = obs.EventSink(str(tmp_path / name))
    prev = obs.set_default_sink(sink)
    try:
        result = fn()
    finally:
        obs.set_default_sink(prev)
        sink.close()
    return result, obs.read_events(str(tmp_path / name))


# ----- schedule grammar: the permanent kinds -------------------------------


def test_parse_schedule_permanent_kinds():
    specs, bad = parse_schedule(
        "decode_dispatch:3:chip_loss:1,fence:0:ici_error,prefill:2:chip_loss"
    )
    assert bad == []
    assert specs == [
        FaultSpec("decode_dispatch", 3, "chip_loss", 1),
        FaultSpec("fence", 0, "ici_error"),
        FaultSpec("prefill", 2, "chip_loss", 0),
    ]


def test_parse_schedule_malformed_permanent_entries_degrade():
    # A fourth field on a non-chip_loss kind, a non-integer or negative
    # device index — each malformed INDIVIDUALLY, valid entries survive.
    specs, bad = parse_schedule(
        "fence:0:ici_error:1,decode_dispatch:1:chip_loss:x,"
        "decode_dispatch:1:chip_loss:-2,decode_dispatch:0:chip_loss:3"
    )
    assert specs == [FaultSpec("decode_dispatch", 0, "chip_loss", 3)]
    assert sorted(bad) == sorted([
        "fence:0:ici_error:1", "decode_dispatch:1:chip_loss:x",
        "decode_dispatch:1:chip_loss:-2",
    ])


def test_injector_fires_permanent_kinds_with_device():
    inj = FaultInjector([FaultSpec("decode_dispatch", 0, "chip_loss", 2),
                         FaultSpec("fence", 0, "ici_error")])
    with pytest.raises(ChipLossFault) as ei:
        inj.fire("decode_dispatch")
    assert ei.value.device_index == 2
    with pytest.raises(IciFault):
        inj.fire("fence")
    assert [f[2] for f in inj.fired] == ["chip_loss", "ici_error"]


def test_classify_splits_transient_and_permanent():
    assert resilience.classify(ChipLossFault("x", 1)) == resilience.PERMANENT
    assert resilience.classify(IciFault("x")) == resilience.PERMANENT
    assert resilience.classify(
        resilience.TransientFault("x")) == resilience.TRANSIENT
    assert resilience.classify(ValueError("user bug")) is None
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    # Permanent markers win even when a transient marker rides along.
    assert resilience.classify(
        XlaRuntimeError("UNAVAILABLE: device halted")
    ) == resilience.PERMANENT
    assert resilience.classify(
        XlaRuntimeError("UNAVAILABLE: transport dead")
    ) == resilience.TRANSIENT
    # recoverable() covers both classes, and still rejects user bugs.
    assert resilience.recoverable(ChipLossFault("x"))
    assert not resilience.recoverable(AssertionError())


def test_kinds_doc_pin():
    # docs/resilience.md's grammar table documents exactly these kinds; a
    # drifted tuple is a doc bug or silent loss of chaos coverage.
    assert KINDS == ("raise-transient", "raise-oom", "hang",
                     "chip_loss", "ici_error")


# ----- shrink ladder -------------------------------------------------------


def test_shrink_ladder():
    # tp=4, one chip dead (3 survivors): 4→2.
    assert tp_serving.shrink_ladder(4, 3) == 2
    # tp=2, one dead: 2→1; tp=1 has nowhere to go.
    assert tp_serving.shrink_ladder(2, 1) == 1
    assert tp_serving.shrink_ladder(1, 0) is None
    # tp_min floors the ladder.
    assert tp_serving.shrink_ladder(4, 3, tp_min=2) == 2
    assert tp_serving.shrink_ladder(4, 3, tp_min=4) is None
    assert tp_serving.shrink_ladder(2, 1, tp_min=2) is None
    # ICI fault: all chips survive, still one rung down.
    assert tp_serving.shrink_ladder(4, 4) == 2
    # Degenerate survivor counts skip rungs that cannot fit.
    assert tp_serving.shrink_ladder(8, 1) == 1


def test_degraded_fallbacks_are_guest_meshable():
    # The host-side half of the degraded-mode contract: every rung the
    # guest ladder can land on is a size the family table can interpret.
    topo = HostTopology.from_accelerator_type("v5litepod-8")
    meshable = set(guest_meshable_counts(topo))
    for count in meshable:
        for rung in degraded_fallbacks(topo, count):
            assert rung == 1 or rung in meshable
    assert degraded_fallbacks(topo, 4) == [2, 1]
    assert degraded_fallbacks(topo, 8) == [4, 2, 1]


def test_tp_min_env_ladder(monkeypatch, tmp_path):
    monkeypatch.delenv(tp_serving.ENV_TP_MIN, raising=False)
    assert tp_serving.tp_min_from_env() == 1
    monkeypatch.setenv(tp_serving.ENV_TP_MIN, "2")
    assert tp_serving.tp_min_from_env() == 2
    monkeypatch.setenv(tp_serving.ENV_TP_MIN, "garbage")
    got, events = _capture_events(
        tmp_path, lambda: tp_serving.tp_min_from_env(label="s1")
    )
    assert got == 1
    evs = [e for e in events if e.get("name") == "tp_min_invalid"]
    assert len(evs) == 1 and evs[0]["reason"].startswith("bad_env")
    monkeypatch.delenv(tp_serving.ENV_DEGRADED, raising=False)
    assert tp_serving.degraded_enabled()
    monkeypatch.setenv(tp_serving.ENV_DEGRADED, "0")
    assert not tp_serving.degraded_enabled()


# ----- the headline: chip loss survives bit-identically --------------------


@pytest.mark.parametrize("overlap", [False, True])
@pytest.mark.parametrize("paged", [False, True])
def test_chip_loss_tp4_shrinks_to_tp2_bit_identical(model, paged, overlap,
                                                    tmp_path):
    """The acceptance criterion: a seeded chip_loss at tp=4 on the
    forced-8-device CPU host shrinks the server to tp=2, completes every
    in-flight and queued request, and greedy outputs are bit-identical
    to the fault-free run — paged and slotted, overlap and lockstep."""
    if jax.device_count() < 4:
        pytest.skip("needs the forced 8-device CPU host")
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    kw = dict(tp=4, overlap=overlap)
    if paged:
        kw.update(kv_pool_tokens=4 * 32, kv_block_size=8)
    _, refres, _ = _serve(params, cfg, prompts, **kw)
    (rids, res, srv), events = _capture_events(
        tmp_path,
        lambda: _serve(
            params, cfg, prompts,
            injector=FaultInjector(
                [FaultSpec("decode_dispatch", 2, "chip_loss", 1)], seed=3
            ),
            **kw,
        ),
    )
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(res[r], refres[i])
    assert srv.failures() == {}
    st = srv.stats()
    assert st["tp_degree"] == 2
    assert st["tp_degraded"] == 1 and st["tp_shrinks"] == 1
    assert st["recoveries"] >= 1
    degraded = [e for e in events if e.get("name") == "tp_degraded"]
    assert len(degraded) == 1
    assert degraded[0]["old_tp"] == 4 and degraded[0]["tp"] == 2
    assert degraded[0]["reason"] == "chip_loss:1"
    assert degraded[0]["survivors"] == 3


def test_chip_loss_with_checkpoint_restore_under_new_sharding(model):
    """Checkpointed lanes restore through _kv_host_upload under the NEW
    (shrunk) sharding — recovered outputs stay bit-identical, and the
    restore path actually engages (restored lanes, not just replays)."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    _, refres, _ = _serve(params, cfg, prompts, budgets=12, tp=4)
    rids, res, srv = _serve(
        params, cfg, prompts, budgets=12, tp=4, checkpoint_rounds=1,
        injector=FaultInjector(
            [FaultSpec("decode_dispatch", 2, "chip_loss", 0)]
        ),
    )
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(res[r], refres[i])
    assert srv.stats()["tp_degree"] == 2
    assert srv.stats()["checkpoints"] >= 1


def test_chip_loss_tp2_shrinks_to_single_chip(model):
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    _, refres, _ = _serve(params, cfg, prompts)
    rids, res, srv = _serve(
        params, cfg, prompts, tp=2,
        injector=FaultInjector([FaultSpec("decode_dispatch", 1, "chip_loss")]),
    )
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(res[r], refres[i])
    assert srv.stats()["tp_degree"] == 1
    assert srv.stats()["tp_degraded"] == 1
    assert srv.failures() == {}


def test_ici_error_shrinks_one_rung(model):
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    _, refres, _ = _serve(params, cfg, prompts)
    rids, res, srv = _serve(
        params, cfg, prompts, tp=4,
        injector=FaultInjector([FaultSpec("decode_dispatch", 2, "ici_error")]),
    )
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(res[r], refres[i])
    assert srv.stats()["tp_degree"] == 2


def test_env_schedule_chip_loss_tp4(model, monkeypatch):
    """The daemon chaos path end-to-end: KATA_TPU_FAULTS carries the
    permanent kind (with device index) into the default injector, and
    the env-built server survives it degraded — the `make chaos`
    chip-loss gate's shape."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    _, refres, _ = _serve(params, cfg, prompts)
    monkeypatch.setenv("KATA_TPU_FAULTS", "decode_dispatch:3:chip_loss:1")
    monkeypatch.setenv("KATA_TPU_FAULTS_SEED", "13")
    srv = GenerationServer(params, cfg, max_batch=2, max_len=32, chunk=4,
                           recovery_backoff_s=0.0, tp=4, tp_min=1)
    rids = [srv.submit(p, 8) for p in prompts]
    res = srv.run()
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(res[r], refres[i])
    assert srv.stats()["tp_degree"] == 2 and srv.failures() == {}


def test_chip_loss_during_restore_shrinks_again(model):
    """A SECOND permanent fault arriving while the first shrink's
    checkpoint restore re-uploads (the pool_alloc seam inside
    _restore_lane — crossing 4 with this workload) must shrink the mesh
    AGAIN before the replay, not replay onto the dead rung: tp=4 → 2 →
    1, outputs still bit-identical, nothing lost."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    kw = dict(overlap=False, checkpoint_rounds=1,
              kv_pool_tokens=4 * 32, kv_block_size=8)
    _, refres, _ = _serve(params, cfg, prompts, budgets=12, **kw)
    sched = [FaultSpec("decode_dispatch", 2, "chip_loss", 1),
             FaultSpec("pool_alloc", 4, "chip_loss", 0)]
    rids, res, srv = _serve(params, cfg, prompts, budgets=12, tp=4,
                            injector=FaultInjector(sched, seed=3), **kw)
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(res[r], refres[i])
    assert srv.failures() == {}
    assert srv.stats()["tp_degree"] == 1
    assert srv.stats()["tp_shrinks"] == 2


def test_chip_loss_during_restore_with_floor_fails_all(model):
    """Same restore-phase second fault, but the tp_min floor forbids the
    second shrink: the load fails loudly and every rid — including the
    survivors that were mid-restore — ends in results or failures."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    sched = [FaultSpec("decode_dispatch", 2, "chip_loss", 1),
             FaultSpec("pool_alloc", 4, "chip_loss", 0)]
    rids, res, srv = _serve(
        params, cfg, prompts, budgets=12, tp=4, tp_min=2,
        overlap=False, checkpoint_rounds=1,
        kv_pool_tokens=4 * 32, kv_block_size=8,
        injector=FaultInjector(sched, seed=3),
    )
    _assert_none_vanish(rids, res, srv.failures())
    assert srv.failures()
    assert srv.stats()["tp_degree"] == 2  # the first shrink held


# ----- no feasible rung: fail loudly, none vanish --------------------------


def _assert_none_vanish(rids, res, fails):
    assert set(rids) == set(res) | set(fails)
    assert not set(res) & set(fails)


def test_chip_loss_at_tp1_fails_all_loudly(model, tmp_path):
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    (rids, res, srv), events = _capture_events(
        tmp_path,
        lambda: _serve(
            params, cfg, prompts,
            injector=FaultInjector(
                [FaultSpec("decode_dispatch", 1, "chip_loss")]
            ),
        ),
    )
    fails = srv.failures()
    _assert_none_vanish(rids, res, fails)
    assert fails and all("ChipLossFault" in v for v in fails.values())
    fatal = [e for e in events if e.get("name") == "chip_loss_fatal"]
    assert len(fatal) == 1 and fatal[0]["why"] == "single_chip"
    failed = [e for e in events if e.get("name") == "request_failed"]
    assert {e["reason"] for e in failed} == {"chip_lost"}


def test_tp_min_floor_fails_all(model, tmp_path):
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    (rids, res, srv), events = _capture_events(
        tmp_path,
        lambda: _serve(
            params, cfg, prompts, tp=4, tp_min=4,
            injector=FaultInjector(
                [FaultSpec("decode_dispatch", 2, "chip_loss", 1)]
            ),
        ),
    )
    _assert_none_vanish(rids, res, srv.failures())
    assert srv.failures()
    assert srv.stats()["tp_degree"] == 4  # never shrank
    fatal = [e for e in events if e.get("name") == "chip_loss_fatal"]
    assert len(fatal) == 1 and fatal[0]["why"] == "tp_min_floor:4"


def test_degraded_kill_switch_fails_all(model, tmp_path):
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    (rids, res, srv), events = _capture_events(
        tmp_path,
        lambda: _serve(
            params, cfg, prompts, tp=4, degraded=False,
            injector=FaultInjector(
                [FaultSpec("decode_dispatch", 2, "chip_loss", 1)]
            ),
        ),
    )
    _assert_none_vanish(rids, res, srv.failures())
    assert srv.failures()
    fatal = [e for e in events if e.get("name") == "chip_loss_fatal"]
    assert len(fatal) == 1 and fatal[0]["why"] == "degraded_disabled"


def test_explicit_bad_tp_min_raises(model):
    cfg, params = model
    with pytest.raises(ValueError, match="tp_min"):
        _server(params, cfg, tp_min=0)


def test_transient_faults_keep_the_mesh(model):
    """The classify() split's other half: a transient fault at tp=4
    recovers WITHOUT shrinking (the pre-ISSUE-10 path, unchanged)."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 8, 6, 3])
    _, refres, _ = _serve(params, cfg, prompts)
    rids, res, srv = _serve(
        params, cfg, prompts, tp=4,
        injector=FaultInjector([FaultSpec("decode_dispatch", 2)]),
    )
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(res[r], refres[i])
    st = srv.stats()
    assert st["tp_degree"] == 4 and st["tp_shrinks"] == 0
    assert st["recoveries"] == 1


# ----- drain at tp>1 with a chip loss mid-drain ----------------------------


def test_drain_tp4_paged_chip_loss_mid_drain(model):
    """Graceful drain over a sharded paged pool with a chip_loss arriving
    MID-DRAIN: started work finishes degraded (bit-identically), the
    never-started tail fails as drained, and every rid ends in exactly
    one of results/failures."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 5, 6, 7])
    _, refres, _ = _serve(params, cfg, prompts, budgets=16)
    srv = _server(
        params, cfg, tp=4, overlap=False,
        kv_pool_tokens=4 * 32, kv_block_size=8,
        injector=FaultInjector([FaultSpec("decode_dispatch", 2,
                                          "chip_loss", 1)]),
    )
    rids = [srv.submit(p, 16) for p in prompts]
    for _ in range(2):  # decode crossings 0 and 1 — clean rounds
        srv.step()
    srv.request_drain(reason="test")
    res = srv.run()  # crossing 2 loses the chip during the drain
    fails = srv.failures()
    _assert_none_vanish(rids, res, fails)
    assert srv.stats()["tp_degree"] == 2  # shrank mid-drain
    assert sorted(res) == rids[:2]  # the started lanes completed
    for rid in res:
        np.testing.assert_array_equal(res[rid], refres[rids.index(rid)])
    assert all(v.startswith("drained") for v in fails.values())


def test_drain_maintenance_file_tp2(model, tmp_path):
    """The production drain trigger composes with a sharded server: the
    maintenance-notice file flips a tp=2 paged server into draining."""
    cfg, params = model
    srv = _server(params, cfg, tp=2, kv_pool_tokens=4 * 32, kv_block_size=8)
    notice = tmp_path / "maintenance"
    wiring = resilience.wire_drain(
        srv, sigterm=False, maintenance_file=str(notice), poll_s=0.01
    )
    try:
        assert wiring.poll_once() is False
        notice.write_text("scheduled")
        assert wiring.poll_once() is True
        assert srv.stats()["draining"]
    finally:
        wiring.stop()


# ----- kv_replicated warning (satellite) -----------------------------------


def test_kv_replicated_warns_once_with_extra_bytes(model, tmp_path):
    """n_kv_heads (2) does not divide tp=4: the pool replicates onto
    every shard — one kv_replicated event with the measured extra bytes,
    emitted once per (server, degree) even across recovery rebuilds."""
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    (rids, res, srv), events = _capture_events(
        tmp_path,
        lambda: _serve(
            params, cfg, prompts, tp=4,
            kv_pool_tokens=4 * 32, kv_block_size=8,
            injector=FaultInjector([FaultSpec("decode_dispatch", 1)]),
        ),
    )
    assert sorted(res) == rids
    assert srv.stats()["recoveries"] == 1  # the rebuild re-placed the pool
    evs = [e for e in events if e.get("name") == "kv_replicated"]
    assert len(evs) == 1
    assert evs[0]["tp"] == 4 and evs[0]["n_kv_heads"] == cfg.n_kv_heads
    assert evs[0]["extra_bytes"] > 0


def test_kv_shardable_degree_does_not_warn(model, tmp_path):
    cfg, params = model
    prompts = _prompts(cfg, [4, 6])
    (_rids, _res, _srv), events = _capture_events(
        tmp_path, lambda: _serve(params, cfg, prompts, tp=2)
    )
    assert not [e for e in events if e.get("name") == "kv_replicated"]


# ----- stats / knob plumbing -----------------------------------------------


def test_stats_schema_always_has_degraded_fields(model):
    cfg, params = model
    srv = _server(params, cfg)
    st = srv.stats()
    assert st["tp_degraded"] == 0 and st["tp_shrinks"] == 0
    assert st["tp_degree"] == 1


def test_allocator_injects_tp_min_env_and_config_validates():
    from kata_xpu_device_plugin_tpu.cdi import constants as C
    from kata_xpu_device_plugin_tpu.config import Config
    from kata_xpu_device_plugin_tpu.discovery.tpu import (
        TpuChip,
        TpuInventory,
    )
    from kata_xpu_device_plugin_tpu.plugin import TpuAllocator

    inv = TpuInventory(
        chips=(TpuChip(index=0, dev_path="/dev/accel0"),
               TpuChip(index=1, dev_path="/dev/accel1")),
        topology=HostTopology.from_accelerator_type("v5litepod-8"),
        model_suffix="TPU_V5E",
    )
    alive = lambda _chip: True  # noqa: E731 — no real /dev in this test
    wired = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive,
        serving_tp=2, serving_tp_min=2,
    ).allocate(["0", "1"])
    assert wired.envs[C.ENV_SERVING_TP_MIN] == "2"
    bare = TpuAllocator(
        lambda: inv, "google.com", "tpu", revalidate=alive
    ).allocate(["0"])
    assert C.ENV_SERVING_TP_MIN not in bare.envs
    assert Config(serving_tp_min=2).serving_tp_min == 2
    assert Config().serving_tp_min == 0
    with pytest.raises(ValueError, match="serving-tp-min"):
        Config(serving_tp_min=-1)
    with pytest.raises(ValueError, match="serving-tp-min"):
        Config(serving_tp=2, serving_tp_min=4)


# ----- daemon half: allocation journal + health quarantine -----------------


def test_allocation_journal_roundtrip(tmp_path):
    from kata_xpu_device_plugin_tpu.plugin import AllocationJournal

    path = str(tmp_path / "state" / "allocations.json")
    j = AllocationJournal(path)
    j.record("google.com/tpu", ["1", "0"])
    j.record("google.com/tpu", ["2", "3"])
    # Reload from disk: groups survive, ids normalized/sorted.
    j2 = AllocationJournal(path)
    assert j2.allocations("google.com/tpu") == [("0", "1"), ("2", "3")]
    # A re-allocation of a freed device supersedes its old entry.
    j2.record("google.com/tpu", ["0"])
    assert AllocationJournal(path).allocations("google.com/tpu") == [
        ("0",), ("0", "1"), ("2", "3")
    ]


def test_journal_reconcile_emits_and_drops_orphans(tmp_path):
    from kata_xpu_device_plugin_tpu.plugin import AllocationJournal

    path = str(tmp_path / "allocations.json")
    j = AllocationJournal(path)
    j.record("google.com/tpu", ["0", "1"])
    j.record("google.com/tpu", ["2", "3"])
    fresh = AllocationJournal(path)

    def run():
        return fresh.reconcile("google.com/tpu", {"0", "1", "2"})

    (rec, orp), events = _capture_events(tmp_path, run)
    assert (rec, orp) == (1, 1)
    ok = [e for e in events if e.get("name") == "alloc_reconciled"]
    bad = [e for e in events if e.get("name") == "alloc_orphaned"]
    assert len(ok) == 1 and ok[0]["devices"] == "0,1"
    assert len(bad) == 1 and bad[0]["devices"] == "2,3"
    assert bad[0]["missing"] == "3"
    # Orphaned entries dropped and the drop persisted.
    assert AllocationJournal(path).allocations("google.com/tpu") == [
        ("0", "1")
    ]


def test_journal_corrupt_or_missing_file_degrades(tmp_path):
    from kata_xpu_device_plugin_tpu.plugin import AllocationJournal

    missing = AllocationJournal(str(tmp_path / "nope.json"))
    assert missing.allocations("google.com/tpu") == []
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    j = AllocationJournal(str(bad))
    assert j.allocations("google.com/tpu") == []
    # And it recovers to a writable journal.
    j.record("google.com/tpu", ["0"])
    assert AllocationJournal(str(bad)).allocations("google.com/tpu") == [
        ("0",)
    ]


def test_journal_reconcile_never_touches_health(tmp_path):
    """The zero-spurious-flaps half of the acceptance criterion: a
    restart reconcile with a populated journal emits allocation events
    only — no health transition, no subscriber wake-up in the
    ListAndWatch path."""
    from kata_xpu_device_plugin_tpu.plugin import AllocationJournal
    from kata_xpu_device_plugin_tpu.plugin.api import glue
    from kata_xpu_device_plugin_tpu.plugin.server import (
        DeviceState,
        WatchedDevice,
    )

    state = DeviceState([WatchedDevice(id=str(i)) for i in range(4)])
    q = state.subscribe()
    path = str(tmp_path / "allocations.json")
    j = AllocationJournal(path)
    j.record("google.com/tpu", ["0", "1"])
    j.record("google.com/tpu", ["3"])
    fresh = AllocationJournal(path)
    fresh.reconcile("google.com/tpu", {"0", "1", "2"})  # 3 vanished
    assert q.qsize() == 0  # no ListAndWatch wake-up
    assert all(d.health == glue.HEALTHY for d in state.snapshot())


def test_health_flip_emits_quarantine_and_readmit_events(tmp_path):
    """Per-chip health quarantine (ISSUE 10): an Unhealthy flip emits
    chip_quarantined, recovery emits chip_readmitted — joinable against
    the guest's tp_degraded stream on the same incident."""
    from kata_xpu_device_plugin_tpu.plugin import HealthWatcher
    from kata_xpu_device_plugin_tpu.plugin.api import glue
    from kata_xpu_device_plugin_tpu.plugin.server import (
        DeviceState,
        WatchedDevice,
    )

    dev = tmp_path / "accel0"
    dev.write_text("")  # regular file: existence is the health signal

    class _Plugin:
        resource_name = "google.com/tpu"
        stopped = False
        serving = True
        socket_dir = str(tmp_path)
        socket_path = str(tmp_path / "sock")
        state = DeviceState([
            WatchedDevice(id="0", watch_paths=(str(dev),))
        ])

    plugin = _Plugin()
    (tmp_path / "sock").write_text("")  # no restart path in this test
    watcher = HealthWatcher([plugin], use_inotify=False)

    def run():
        watcher.evaluate()          # healthy, no flip
        dev.unlink()
        watcher.evaluate()          # flip to UNHEALTHY
        dev.write_text("")
        watcher.evaluate()          # flip back

    _, events = _capture_events(tmp_path, run)
    quarantined = [e for e in events if e.get("name") == "chip_quarantined"]
    readmitted = [e for e in events if e.get("name") == "chip_readmitted"]
    assert len(quarantined) == 1 and quarantined[0]["device"] == "0"
    assert len(readmitted) == 1 and readmitted[0]["device"] == "0"
    assert plugin.state.get("0").health == glue.HEALTHY
