#!/usr/bin/env python3
"""Decode-step fine ablation: attribute the ~1.1 ms/step of non-weight time.

exp_decode.py showed attention's non-weight cost is only ~0.11 ms/step, so
the pallas decode kernel had nothing to win. This script strips the fused
decode step one feature at a time (numerics deliberately wrong in the
stripped variants — timing only) to find where the rest goes:

  full          the real fused-layout decode step (oracle for bench)
  no-norms      rms_norm -> identity
  no-rope       skip rotary embedding on q/k
  no-cachewrite attend to the pre-filled cache without writing new k/v
  no-softmax    logits @ v without max/exp/sum normalization
  matmuls-only  just wqkv/wo/gateup/down/unembed matmuls + residuals
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, "/root/repo")

from kata_xpu_device_plugin_tpu.models import gemma_2b_bench
from kata_xpu_device_plugin_tpu.models.transformer import (
    fuse_decoder_params,
    init_params,
    rms_norm,
    rope,
)

cfg = gemma_2b_bench()
B, PROMPT, STEPS = 8, 128, 128
MAX_LEN = PROMPT + STEPS

params = jax.jit(
    lambda k: fuse_decoder_params(init_params(k, cfg, dtype=jnp.bfloat16))
)(jax.random.PRNGKey(0))
jax.block_until_ready(params)
ideal_ms = cfg.num_params() * 2 / 819e9 * 1e3
print(f"params {cfg.num_params()/1e9:.3f}G -> ideal {ideal_ms:.3f} ms/step")


def make_decode(no_norms=False, no_rope=False, no_cachewrite=False,
                no_softmax=False, matmuls_only=False):
    if matmuls_only:
        no_norms = no_rope = no_cachewrite = no_softmax = True

    def norm(x, scale):
        return x if no_norms else rms_norm(x, scale, cfg.norm_eps)

    @jax.jit
    def dec(fp, caches, tok, pos):
        def step(carry, _):
            caches, tok, pos = carry
            positions = jnp.full((B, 1), pos, jnp.int32)
            x = fp["embed"].astype(cfg.dtype)[tok[:, None]] * jnp.asarray(
                jnp.sqrt(cfg.d_model), cfg.dtype
            )

            def body(x, layer_and_cache):
                layer, (ck, cv) = layer_and_cache
                h = norm(x, layer["attn_norm"])
                qkv = h @ layer["wqkv"].astype(h.dtype)
                q = qkv[..., : cfg.q_dim].reshape(B, 1, cfg.n_heads, cfg.head_dim)
                k = qkv[..., cfg.q_dim : cfg.q_dim + cfg.kv_dim].reshape(
                    B, 1, cfg.n_kv_heads, cfg.head_dim
                )
                v = qkv[..., cfg.q_dim + cfg.kv_dim :].reshape(
                    B, 1, cfg.n_kv_heads, cfg.head_dim
                )
                if not no_rope:
                    q = rope(q, positions, cfg.rope_theta)
                    k = rope(k, positions, cfg.rope_theta)
                if not no_cachewrite:
                    ck = lax.dynamic_update_slice(
                        ck, k.astype(ck.dtype), (0, pos, 0, 0)
                    )
                    cv = lax.dynamic_update_slice(
                        cv, v.astype(cv.dtype), (0, pos, 0, 0)
                    )
                if matmuls_only:
                    attn = q.reshape(B, 1, cfg.q_dim)
                else:
                    G = cfg.n_heads // cfg.n_kv_heads
                    qg = q.reshape(B, cfg.n_kv_heads, G, cfg.head_dim)
                    logits = jnp.einsum(
                        "bhgd,bkhd->bhgk", qg, ck,
                        preferred_element_type=jnp.float32,
                    ) * (1.0 / float(cfg.head_dim) ** 0.5)
                    mask = jnp.arange(MAX_LEN)[None, :] <= pos
                    logits = jnp.where(mask[None, None], logits, -1e30)
                    p = logits if no_softmax else jax.nn.softmax(logits, axis=-1)
                    attn = jnp.einsum(
                        "bhgk,bkhd->bhgd", p.astype(cv.dtype), cv,
                        preferred_element_type=jnp.float32,
                    ).astype(x.dtype).reshape(B, 1, cfg.q_dim)
                x = x + attn @ layer["wo"].astype(x.dtype)
                h = norm(x, layer["mlp_norm"])
                gu = h @ layer["w_gateup"].astype(h.dtype)
                gate = jax.nn.gelu(gu[..., : cfg.d_ff], approximate=True)
                x = x + (gate * gu[..., cfg.d_ff :]) @ layer["w_down"].astype(x.dtype)
                return x, (ck, cv)

            x, caches = lax.scan(body, x, (fp["layers"], caches))
            x = norm(x, fp["final_norm"])
            logits = jnp.matmul(
                x, fp["embed"].T.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        (_, _, _), out = lax.scan(step, (caches, tok, pos), None, length=STEPS)
        return out.T

    return dec


def timeit(name, fn):
    shape = (cfg.n_layers, B, MAX_LEN, cfg.n_kv_heads, cfg.head_dim)
    caches = (jnp.zeros(shape, jnp.bfloat16), jnp.zeros(shape, jnp.bfloat16))
    tok = jnp.zeros((B,), jnp.int32)
    pos = jnp.int32(PROMPT)
    np.asarray(fn(params, caches, tok, pos))  # compile
    best = float("inf")
    for s in range(3):
        tok2 = jax.random.randint(jax.random.PRNGKey(s), (B,), 0, cfg.vocab_size)
        np.asarray(tok2)
        t0 = time.perf_counter()
        np.asarray(fn(params, caches, tok2, pos))
        best = min(best, time.perf_counter() - t0)
    ms = best / STEPS * 1e3
    print(f"{name:16s} {ms:7.3f} ms/step  roofline_frac={ideal_ms/ms:.3f}")
    return ms


timeit("full", make_decode())
timeit("no-norms", make_decode(no_norms=True))
timeit("no-rope", make_decode(no_rope=True))
timeit("no-cachewrite", make_decode(no_cachewrite=True))
timeit("no-softmax", make_decode(no_softmax=True))
timeit("matmuls-only", make_decode(matmuls_only=True))
