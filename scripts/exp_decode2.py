#!/usr/bin/env python3
"""Variant 2: attention overhead reduction — combined KV cache (one
dynamic_update_slice), direct dot attention without einsum relayouts."""
from __future__ import annotations
import sys, time
import jax, jax.numpy as jnp, numpy as np
from jax import lax
sys.path.insert(0, "/root/repo")
from kata_xpu_device_plugin_tpu.models import gemma_2b_bench
from kata_xpu_device_plugin_tpu.models.transformer import init_params, rms_norm, rope

cfg = gemma_2b_bench()
B, PROMPT, STEPS = 8, 128, 128
MAX_LEN = PROMPT + STEPS
key = jax.random.PRNGKey(0)
params = jax.jit(lambda k: init_params(k, cfg, dtype=jnp.bfloat16))(key)
jax.block_until_ready(params)
param_bytes = cfg.num_params() * 2
ideal_ms = param_bytes / 819e9 * 1e3


def fuse(params):
    l = params["layers"]
    return {
        "embed": params["embed"], "final_norm": params["final_norm"],
        "layers": {
            "attn_norm": l["attn_norm"],
            "wqkv": jnp.concatenate([l["wq"], l["wk"], l["wv"]], axis=2),
            "wo": l["wo"], "mlp_norm": l["mlp_norm"],
            "w_gateup": jnp.concatenate([l["w_gate"], l["w_up"]], axis=2),
            "w_down": l["w_down"],
        },
    }

fparams = jax.jit(fuse)(params)
jax.block_until_ready(fparams)

# Combined cache: [L, B, max_len, 2*KV*D] (k then v flattened)
KVD = cfg.kv_dim

def make_decode(combined=True):
    @jax.jit
    def dec(fp, caches, tok, pos):
        def step(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            x = fp["embed"].astype(cfg.dtype)[tok[:, None]] * jnp.asarray(
                jnp.sqrt(cfg.d_model), cfg.dtype)

            def body(x, layer_and_cache):
                layer, cache = layer_and_cache
                h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
                qkv = h @ layer["wqkv"].astype(h.dtype)
                q = qkv[..., :cfg.q_dim].reshape(B, 1, cfg.n_heads, cfg.head_dim)
                kv = qkv[..., cfg.q_dim:]  # [B, 1, 2*KVD]
                q = rope(q, positions, cfg.rope_theta)
                k = rope(kv[..., :KVD].reshape(B, 1, cfg.n_kv_heads, cfg.head_dim),
                         positions, cfg.rope_theta)
                kv = jnp.concatenate([k.reshape(B, 1, KVD), kv[..., KVD:]], -1)
                cache = lax.dynamic_update_slice(
                    cache, kv.astype(cache.dtype), (0, pos[0], 0))
                ck = cache[..., :KVD].reshape(B, MAX_LEN, cfg.n_kv_heads, cfg.head_dim)
                cv = cache[..., KVD:].reshape(B, MAX_LEN, cfg.n_kv_heads, cfg.head_dim)
                # direct GQA dot: q [B,1,H,D] -> [B, KV, G, D]
                G = cfg.n_heads // cfg.n_kv_heads
                qg = q.reshape(B, cfg.n_kv_heads, G, cfg.head_dim)
                logits = jnp.einsum("bhgd,bkhd->bhgk", qg, ck,
                                    preferred_element_type=jnp.float32)
                logits *= 1.0 / float(cfg.head_dim) ** 0.5
                mask = jnp.arange(MAX_LEN)[None, :] <= pos[0]
                logits = jnp.where(mask[None, None], logits, -1e30)
                p = jax.nn.softmax(logits, axis=-1)
                attn = jnp.einsum("bhgk,bkhd->bhgd", p.astype(cv.dtype), cv,
                                  preferred_element_type=jnp.float32)
                attn = attn.astype(x.dtype).reshape(B, 1, cfg.q_dim)
                x = x + attn @ layer["wo"].astype(x.dtype)
                h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
                gu = h @ layer["w_gateup"].astype(h.dtype)
                gate = jax.nn.gelu(gu[..., :cfg.d_ff], approximate=True)
                x = x + (gate * gu[..., cfg.d_ff:]) @ layer["w_down"].astype(x.dtype)
                return x, cache

            x, caches = lax.scan(body, x, (fp["layers"], caches))
            x = rms_norm(x, fp["final_norm"], cfg.norm_eps)
            logits = jnp.matmul(x, fp["embed"].T.astype(cfg.dtype),
                                preferred_element_type=jnp.float32)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        (_, _, _), out = lax.scan(step, (caches, tok, pos), None, length=STEPS)
        return out.T
    return dec


def timeit(name, fn):
    caches = jnp.zeros((cfg.n_layers, B, MAX_LEN, 2 * KVD), jnp.bfloat16)
    tok = jnp.zeros((B,), jnp.int32)
    pos = jnp.full((B,), PROMPT, jnp.int32)
    np.asarray(fn(fparams, caches, tok, pos))
    best = float("inf")
    for s in range(3):
        tok2 = jax.random.randint(jax.random.PRNGKey(s), (B,), 0, cfg.vocab_size)
        np.asarray(tok2)
        t0 = time.perf_counter()
        np.asarray(fn(fparams, caches, tok2, pos))
        best = min(best, time.perf_counter() - t0)
    ms = best / STEPS * 1e3
    print(f"{name:24s} {ms:7.3f} ms/step  roofline_frac={ideal_ms/ms:.3f}")

timeit("combined-cache", make_decode())
