#!/usr/bin/env python3
"""Opportunistic TPU bench watchdog (VERDICT r4 next-step #1).

The driver runs ``bench.py`` exactly once, at end-of-round; with a tunnel
that wedges for hours at a time that policy maximises the chance of
measuring nothing (rounds 3 and 4 both ended with CPU-fallback bench
lines despite two rounds of unmeasured perf work). This watchdog inverts
the schedule: it probes the tunnel cheaply every few minutes for the
whole round and, on the FIRST healthy probe, runs the full ``bench.py``
and banks the JSON to a dated, committed file — so a single healthy
window at any point in the round is enough to put a driver-verifiable
TPU number on record.

Design notes:
- The probe is a killable subprocess doing one tiny dispatch (same shape
  as bench.py's supervisor probe): a hang means the tunnel is wedged —
  we SIGKILL the probe and sleep, we do NOT launch the full bench.
- A healthy probe immediately runs ``python bench.py`` with a generous
  timeout (the tunnel may re-wedge mid-bench; bench.py's own supervisor
  budget bounds it). Only a line with ``platform == "tpu"`` counts.
- Success banks ``BENCH_TPU_<utcstamp>.json`` at the repo root and
  git-commits it, then keeps watching at a long interval so later,
  faster code can bank improved numbers (every bank is a separate file;
  nothing is overwritten).
- All activity appends to ``artifacts/bench_watch.log`` so the round's
  tunnel health history is reconstructable.

Usage: ``python scripts/bench_when_healthy.py [--interval 300] [--once]``
or ``make bench-watch``.
"""
from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "artifacts", "bench_watch.log")

sys.path.insert(0, REPO)
import bench as _bench  # reuse probe_tunnel: one probe implementation, not two


def log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
    line = f"[{stamp}] {msg}"
    print(line, flush=True)
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float) -> tuple[bool, str]:
    """bench.py's tunnel probe: tiny dispatch, platform must be tpu.
    ``timeout_s`` is passed through as the probe's own cap — without the
    override, bench's env default (90 s) would silently clamp larger
    --probe-timeout values."""
    ok, hung, msg = _bench.probe_tunnel(
        time.monotonic() + timeout_s, timeout_s=timeout_s
    )
    if hung:
        return False, "hung"
    return ok, msg or "ok"


def run_bench(timeout_s: float) -> dict | None:
    """Run the full bench; return the parsed headline dict iff platform is tpu."""
    env = dict(os.environ)
    env.setdefault("KATA_TPU_BENCH_W8A8", "1")  # verdict: W8A8 has never been measured
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            timeout=timeout_s,
            capture_output=True,
            text=True,
            cwd=REPO,
            env=env,
        )
    except subprocess.TimeoutExpired:
        log("bench.py exceeded watchdog timeout (tunnel likely re-wedged mid-run)")
        return None
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    if not lines:
        log(f"bench.py produced no JSON line (rc={r.returncode}); stderr tail: "
            + r.stderr[-300:].replace("\n", " | "))
        return None
    try:
        head = json.loads(lines[0])
    except json.JSONDecodeError:
        log(f"unparseable bench line: {lines[0][:200]}")
        return None
    if head.get("platform") != "tpu":
        log(f"bench completed but platform={head.get('platform')!r} — not banking")
        return None
    # Side-section lines are parsed best-effort: a worker killed mid-print
    # (tunnel re-wedge — the exact scenario this watchdog exists for) can
    # leave a truncated line, which must not crash the long-running loop.
    parsed, bad = [], 0
    for ln in lines:
        try:
            parsed.append(json.loads(ln))
        except json.JSONDecodeError:
            bad += 1
    if bad:
        log(f"dropped {bad} truncated side-section line(s)")
    head["_all_lines"] = parsed
    return head


def bank(head: dict) -> str:
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    path = os.path.join(REPO, f"BENCH_TPU_{stamp}.json")
    with open(path, "w") as f:
        json.dump(head, f, indent=2)
        f.write("\n")
    # Commit ONLY the banked JSON (pathspec'd: the watchdog shares this
    # checkout with the builder, and a bare `git commit` could sweep the
    # builder's staged work under the wrong message). The log is *.log-
    # gitignored, so it needs -f. A failed commit (lock contention with the
    # builder's own git ops) is logged but not fatal: the JSON exists on
    # disk and the driver's end-of-round sweep commits leftovers.
    rel = os.path.basename(path)
    subprocess.run(["git", "add", "-f", rel, os.path.basename(LOG)],
                   cwd=REPO, capture_output=True)
    r = subprocess.run(
        ["git", "commit", "-m", f"Bank opportunistic TPU bench capture {stamp}",
         "--", rel, os.path.basename(LOG)],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    if r.returncode != 0:
        log(f"git commit of {rel} failed (rc={r.returncode}): "
            + (r.stderr or r.stdout)[-200:].replace("\n", " | "))
    return path


def main() -> int:  # lint: allow(JX004) wall-clock probe scheduler, no jax compute timed here
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between probes while the tunnel is down")
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--bench-timeout", type=float, default=1800.0,
                    help="hard cap on one bench.py run (its own budget is 23 min)")
    ap.add_argument("--settle-interval", type=float, default=3600.0,
                    help="probe cadence after a successful bank")
    ap.add_argument("--once", action="store_true",
                    help="exit after the first successful bank")
    args = ap.parse_args()

    log(f"watchdog start (interval={args.interval}s probe={args.probe_timeout}s)")
    banked = 0
    while True:
        t0 = time.monotonic()
        ok, why = probe(args.probe_timeout)
        if ok:
            log("probe HEALTHY — launching full bench")
            head = run_bench(args.bench_timeout)
            if head is not None:
                path = bank(head)
                banked += 1
                log(f"BANKED {path}: {head.get('value')} {head.get('unit')} "
                    f"vs_baseline={head.get('vs_baseline')}")
                if args.once:
                    return 0
            else:
                log("bench attempt did not yield a TPU line")
        else:
            log(f"probe not healthy ({why}) — tunnel down")
        interval = args.settle_interval if banked else args.interval
        elapsed = time.monotonic() - t0
        time.sleep(max(10.0, interval - elapsed))


if __name__ == "__main__":
    sys.exit(main())
