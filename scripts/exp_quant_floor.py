#!/usr/bin/env python3
"""int8 decode floor: is 0.67 of the int8 roofline the compiler ceiling?

Times the real int8 decode step and an int8 matmuls-only variant (weights
streamed as int8, dequant-scale on the activation, everything else
stripped) — the int8 analogue of exp_decode.py --suite strip's bf16 floor measurement.
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, "/root/repo")

from kata_xpu_device_plugin_tpu.compat.jaxapi import enable_compilation_cache

# Persistent XLA compile cache (ISSUE 3): sweep reruns skip the
# multi-second recompiles; KATA_TPU_COMPILE_CACHE=0 disables.
enable_compilation_cache()

from kata_xpu_device_plugin_tpu.models import gemma_2b_bench
from kata_xpu_device_plugin_tpu.models.transformer import (
    decode,
    fuse_decoder_params,
    init_kv_caches,
    init_params,
)
from kata_xpu_device_plugin_tpu.ops.quant import (
    params_hbm_bytes,
    quantize_decoder_params,
    weight_matmul,
)

cfg = gemma_2b_bench()
B, PROMPT, STEPS = 8, 128, 128
MAX_LEN = PROMPT + STEPS

params = jax.jit(
    lambda k: fuse_decoder_params(init_params(k, cfg, dtype=jnp.bfloat16))
)(jax.random.PRNGKey(0))
qparams = jax.jit(quantize_decoder_params)(params)
jax.block_until_ready(qparams)

ideal_ms = params_hbm_bytes(qparams) / 819e9 * 1e3
print(f"int8 bytes {params_hbm_bytes(qparams)/1e9:.3f}G -> ideal {ideal_ms:.3f} ms/step")


@jax.jit
def matmuls_only(fp, tok, pos):
    def step(carry, _):
        tok, pos = carry
        x = fp["embed"].astype(cfg.dtype)[tok[:, None]]

        def body(x, layer):
            qkv = weight_matmul(x, layer["wqkv"])
            x = x + weight_matmul(qkv[..., : cfg.q_dim], layer["wo"])
            gu = weight_matmul(x, layer["w_gateup"])
            x = x + weight_matmul(gu[..., : cfg.d_ff], layer["w_down"])
            return x, None

        x, _ = lax.scan(body, x, fp["layers"])
        logits = jnp.matmul(
            x, fp["embed"].T.astype(cfg.dtype), preferred_element_type=jnp.float32
        )
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return (nxt, pos + 1), nxt

    (_, _), out = lax.scan(step, (tok, pos), None, length=STEPS)
    return out.T


def timeit(name, fn):  # jaxguard: hot
    np.asarray(fn(qparams, jnp.zeros((B,), jnp.int32), jnp.int32(PROMPT)))  # compile  # jaxguard: allow(JG101, JG404) defensive: fn is an opaque jitted closure the dataflow cannot taint; warm-up fence, outside the timed window
    best = float("inf")
    for s in range(3):
        tok2 = jax.random.randint(jax.random.PRNGKey(s), (B,), 0, cfg.vocab_size)
        np.asarray(tok2)  # jaxguard: allow(JG101) pre-materialize the input OUTSIDE the timed window
        t0 = time.perf_counter()
        np.asarray(fn(qparams, tok2, jnp.int32(PROMPT)))  # jaxguard: allow(JG101, JG404) defensive: fn is an opaque jitted closure the dataflow cannot taint; the transfer IS the timing fence (JX004)
        best = min(best, time.perf_counter() - t0)
    ms = best / STEPS * 1e3
    print(f"{name:16s} {ms:7.3f} ms/step  int8_roofline_frac={ideal_ms/ms:.3f}")


caches = init_kv_caches(cfg, B, MAX_LEN)
# PROMPT as the static python int, NOT int(pos): pos is a device scalar, and
# int() on it is a device→host sync INSIDE the timed window — the stray hot-
# path sync jaxguard (JG101) exists to catch; it also skewed full-int8
# against matmuls-only, which never paid the extra round-trip.
timeit("full-int8", lambda p, tok, pos: decode(p, caches, tok, PROMPT, cfg, STEPS))
timeit("matmuls-only", matmuls_only)
