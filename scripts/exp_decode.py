#!/usr/bin/env python3
"""Decode-step microbenchmark: where does the non-roofline 19% go?

Times structural variants of the Gemma-2B decode step on the attached chip:
  v0  current forward (layer lax.scan, separate wq/wk/wv and gate/up matmuls)
  v1  fused wqkv [d, q+2kv] and w_gateup [d, 2f] matmuls
  v2  v1 + layer-scan unroll
Prints ms/step and implied roofline fraction for each.
"""
from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, "/root/repo")

from kata_xpu_device_plugin_tpu.models import gemma_2b_bench
from kata_xpu_device_plugin_tpu.models.transformer import (
    forward,
    init_kv_caches,
    init_params,
    rms_norm,
    rope,
)

cfg = gemma_2b_bench()
B, PROMPT, STEPS = 8, 128, 128
MAX_LEN = PROMPT + STEPS

key = jax.random.PRNGKey(0)
params = jax.jit(lambda k: init_params(k, cfg, dtype=jnp.bfloat16))(key)
jax.block_until_ready(params)

param_bytes = cfg.num_params() * 2
HBM = 819e9
ideal_ms = param_bytes / HBM * 1e3
print(f"params {cfg.num_params()/1e9:.3f}G -> ideal {ideal_ms:.3f} ms/step")


def fuse(params):
    l = params["layers"]
    return {
        "embed": params["embed"],
        "final_norm": params["final_norm"],
        "layers": {
            "attn_norm": l["attn_norm"],
            "wqkv": jnp.concatenate([l["wq"], l["wk"], l["wv"]], axis=2),
            "wo": l["wo"],
            "mlp_norm": l["mlp_norm"],
            "w_gateup": jnp.concatenate([l["w_gate"], l["w_up"]], axis=2),
            "w_down": l["w_down"],
        },
    }


fparams = jax.jit(fuse)(params)
jax.block_until_ready(fparams)


def fused_layer(x, layer, positions, kv_cache, cache_offset):
    Bq, S, _ = x.shape
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    qkv = h @ layer["wqkv"].astype(h.dtype)
    q = qkv[..., : cfg.q_dim].reshape(Bq, S, cfg.n_heads, cfg.head_dim)
    k = qkv[..., cfg.q_dim : cfg.q_dim + cfg.kv_dim].reshape(
        Bq, S, cfg.n_kv_heads, cfg.head_dim
    )
    v = qkv[..., cfg.q_dim + cfg.kv_dim :].reshape(Bq, S, cfg.n_kv_heads, cfg.head_dim)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    ck, cv = kv_cache
    ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, cache_offset, 0, 0))
    cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, cache_offset, 0, 0))
    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention

    attn = reference_attention(q, ck, cv, causal=True, q_offset=cache_offset)
    x = x + attn.reshape(Bq, S, cfg.q_dim) @ layer["wo"].astype(x.dtype)
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gu = h @ layer["w_gateup"].astype(h.dtype)
    gate = jax.nn.gelu(gu[..., : cfg.d_ff], approximate=True)
    x = x + (gate * gu[..., cfg.d_ff :]) @ layer["w_down"].astype(x.dtype)
    return x, (ck, cv)


def fused_forward(fp, tokens, positions, caches, cache_offset, unroll=1):
    x = fp["embed"].astype(cfg.dtype)[tokens] * jnp.asarray(
        jnp.sqrt(cfg.d_model), cfg.dtype
    )

    def body(x, layer_and_cache):
        layer, (ck, cv) = layer_and_cache
        x, new_cache = fused_layer(x, layer, positions, (ck, cv), cache_offset)
        return x, new_cache

    x, new_caches = lax.scan(body, x, (fp["layers"], caches), unroll=unroll)
    x = rms_norm(x, fp["final_norm"], cfg.norm_eps)
    logits = jnp.matmul(
        x, fp["embed"].T.astype(cfg.dtype), preferred_element_type=jnp.float32
    )
    return logits, new_caches


def make_decode_v0():
    @jax.jit
    def dec(params, caches, tok, pos):
        def step(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            logits, caches = forward(
                params, tok[:, None], cfg, positions=positions,
                kv_caches=caches, cache_offset=pos[0],
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        (_, _, _), out = lax.scan(step, (caches, tok, pos), None, length=STEPS)
        return out.T

    return dec


def make_decode_fused(unroll):
    @jax.jit
    def dec(fp, caches, tok, pos):
        def step(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            logits, caches = fused_forward(
                fp, tok[:, None], positions, caches, pos[0], unroll=unroll
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        (_, _, _), out = lax.scan(step, (caches, tok, pos), None, length=STEPS)
        return out.T

    return dec


def timeit(name, fn, p):
    caches = init_kv_caches(cfg, B, MAX_LEN)
    tok = jnp.zeros((B,), jnp.int32)
    pos = jnp.full((B,), PROMPT, jnp.int32)
    np.asarray(fn(p, caches, tok, pos))  # compile
    best = float("inf")
    for s in range(3):
        tok2 = jax.random.randint(jax.random.PRNGKey(s), (B,), 0, cfg.vocab_size)
        np.asarray(tok2)
        t0 = time.perf_counter()
        np.asarray(fn(p, caches, tok2, pos))
        best = min(best, time.perf_counter() - t0)
    ms = best / STEPS * 1e3
    print(f"{name:24s} {ms:7.3f} ms/step  roofline_frac={ideal_ms/ms:.3f}")
    return ms


timeit("v0 current", make_decode_v0(), params)
timeit("v1 fused", make_decode_fused(1), fparams)
timeit("v2 fused+unroll3", make_decode_fused(3), fparams)
timeit("v3 fused+unroll6", make_decode_fused(6), fparams)


def make_decode_ablate(skip_attn=False, skip_mlp=False, skip_unembed=False):
    def layer_fn(x, layer, positions, kv_cache, cache_offset):
        Bq, S, _ = x.shape
        ck, cv = kv_cache
        if not skip_attn:
            h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
            qkv = h @ layer["wqkv"].astype(h.dtype)
            q = qkv[..., : cfg.q_dim].reshape(Bq, S, cfg.n_heads, cfg.head_dim)
            k = qkv[..., cfg.q_dim : cfg.q_dim + cfg.kv_dim].reshape(
                Bq, S, cfg.n_kv_heads, cfg.head_dim
            )
            v = qkv[..., cfg.q_dim + cfg.kv_dim :].reshape(
                Bq, S, cfg.n_kv_heads, cfg.head_dim
            )
            q = rope(q, positions, cfg.rope_theta)
            k = rope(k, positions, cfg.rope_theta)
            ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, cache_offset, 0, 0))
            cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, cache_offset, 0, 0))
            from kata_xpu_device_plugin_tpu.ops.attention import reference_attention

            attn = reference_attention(q, ck, cv, causal=True, q_offset=cache_offset)
            x = x + attn.reshape(Bq, S, cfg.q_dim) @ layer["wo"].astype(x.dtype)
        if not skip_mlp:
            h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
            gu = h @ layer["w_gateup"].astype(h.dtype)
            gate = jax.nn.gelu(gu[..., : cfg.d_ff], approximate=True)
            x = x + (gate * gu[..., cfg.d_ff :]) @ layer["w_down"].astype(x.dtype)
        return x, (ck, cv)

    @jax.jit
    def dec(fp, caches, tok, pos):
        def step(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            x = fp["embed"].astype(cfg.dtype)[tok[:, None]] * jnp.asarray(
                jnp.sqrt(cfg.d_model), cfg.dtype
            )

            def body(x, layer_and_cache):
                layer, cc = layer_and_cache
                return layer_fn(x, layer, positions, cc, pos[0])

            x, caches = lax.scan(body, x, (fp["layers"], caches))
            x = rms_norm(x, fp["final_norm"], cfg.norm_eps)
            if skip_unembed:
                nxt = x[:, -1, 0].astype(jnp.int32) % cfg.vocab_size
            else:
                logits = jnp.matmul(
                    x, fp["embed"].T.astype(cfg.dtype),
                    preferred_element_type=jnp.float32,
                )
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        (_, _, _), out = lax.scan(step, (caches, tok, pos), None, length=STEPS)
        return out.T

    return dec


timeit("ab full", make_decode_ablate(), fparams)
timeit("ab no-attn", make_decode_ablate(skip_attn=True), fparams)
timeit("ab no-mlp", make_decode_ablate(skip_mlp=True), fparams)
timeit("ab no-unembed", make_decode_ablate(skip_unembed=True), fparams)
