#!/usr/bin/env python3
"""Decode-step microbenchmark suites: where does the non-roofline time go?

One script, three suites (``--suite``), sharing the model setup and the
vary-the-inputs timing loop (the axon tunnel caches identical executions,
see .claude/skills/verify/SKILL.md):

  structural   Structural variants of the Gemma-2B decode step — v0 current
               forward (layer lax.scan, separate wq/wk/wv and gate/up
               matmuls), v1 fused wqkv/w_gateup, v2/v3 fused + layer-scan
               unroll — plus coarse skip-attn / skip-mlp / skip-unembed
               ablations.  RESULT (v5e, r2): fused ≈ +1%, unroll neutral;
               weights stream at ~0.83 of spec roofline — the structural
               ceiling.  (r5 re-run: unroll now measures ~3× SLOWER,
               18.7 vs 6.1 ms/step, on the current jax/libtpu — the
               shipped default of no unroll stands doubly confirmed.)
  cache-layout Attention overhead reduction: one combined KV cache
               ([L,B,T,2*kv_dim], a single dynamic_update_slice per layer)
               and direct GQA dots without einsum relayouts.  RESULT (v5e,
               r2): attention's non-weight cost ≈ 0.11 ms/step — too small
               for a fused decode kernel to win (why ops/decode_attn.py is
               opt-in).
  strip        Fine attribution of the remaining ~1.1 ms/step: strip the
               fused decode step one feature at a time (norms, rope,
               cache-write, softmax; numerics deliberately wrong — timing
               only).  RESULT (v5e, r2): spread across many small XLA ops;
               no single op worth a kernel — only byte reductions (int8
               weights, int8 KV) move decode.

The recorded conclusions above are the measurement provenance BASELINE.md
and docs/architecture.md cite; re-run any suite on the attached chip to
reproduce.  (Consolidates the former exp_decode.py / exp_decode2.py /
exp_decode3.py siblings.)
"""
from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, "/root/repo")

from kata_xpu_device_plugin_tpu.compat.jaxapi import enable_compilation_cache

# Persistent XLA compile cache (ISSUE 3): sweep reruns skip the
# multi-second recompiles; KATA_TPU_COMPILE_CACHE=0 disables.
enable_compilation_cache()

from kata_xpu_device_plugin_tpu.models import gemma_2b_bench
from kata_xpu_device_plugin_tpu.models.transformer import (
    forward,
    fuse_decoder_params,
    init_kv_caches,
    init_params,
    rms_norm,
    rope,
)

cfg = gemma_2b_bench()
B, PROMPT, STEPS = 8, 128, 128
MAX_LEN = PROMPT + STEPS
HBM = 819e9  # v5e spec HBM bandwidth
ideal_ms = cfg.num_params() * 2 / HBM * 1e3

# Initialized by _init() AFTER argparse: --help / a mistyped --suite must
# not pay a 2.5G-param device initialization over the tunnel first.
params = None
fparams = None


def _init() -> None:
    global params, fparams
    if params is not None:
        return
    key = jax.random.PRNGKey(0)
    params = jax.jit(lambda k: init_params(k, cfg, dtype=jnp.bfloat16))(key)
    jax.block_until_ready(params)
    fparams = jax.jit(fuse_decoder_params)(params)
    jax.block_until_ready(fparams)


def timeit(name, fn, p, caches, pos):  # jaxguard: hot
    """Best-of-3 steady-state timing; inputs vary per rep (tunnel caching)."""
    tok = jnp.zeros((B,), jnp.int32)
    np.asarray(fn(p, caches, tok, pos))  # compile  # jaxguard: allow(JG101, JG404) defensive: fn is an opaque jitted closure the dataflow cannot taint; warm-up fence, outside the timed window
    best = float("inf")
    for s in range(3):
        tok2 = jax.random.randint(jax.random.PRNGKey(s), (B,), 0, cfg.vocab_size)
        np.asarray(tok2)  # jaxguard: allow(JG101) pre-materialize the input OUTSIDE the timed window
        t0 = time.perf_counter()
        np.asarray(fn(p, caches, tok2, pos))  # jaxguard: allow(JG101, JG404) defensive: fn is an opaque jitted closure the dataflow cannot taint; the transfer IS the timing fence (JX004)
        best = min(best, time.perf_counter() - t0)
    ms = best / STEPS * 1e3
    print(f"{name:24s} {ms:7.3f} ms/step  roofline_frac={ideal_ms/ms:.3f}")
    return ms


def steps_scan(step):
    """Wrap a single-token step fn into the STEPS-long greedy decode scan."""

    def dec(p, caches, tok, pos):
        (_, _, _), out = lax.scan(step(p), (caches, tok, pos), None, length=STEPS)
        return out.T

    return jax.jit(dec)


# --------------------------------------------------------------- structural

def fused_layer(x, layer, positions, kv_cache, cache_offset):
    Bq, S, _ = x.shape
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    qkv = h @ layer["wqkv"].astype(h.dtype)
    q = qkv[..., : cfg.q_dim].reshape(Bq, S, cfg.n_heads, cfg.head_dim)
    k = qkv[..., cfg.q_dim : cfg.q_dim + cfg.kv_dim].reshape(
        Bq, S, cfg.n_kv_heads, cfg.head_dim
    )
    v = qkv[..., cfg.q_dim + cfg.kv_dim :].reshape(Bq, S, cfg.n_kv_heads, cfg.head_dim)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    ck, cv = kv_cache
    ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, cache_offset, 0, 0))
    cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, cache_offset, 0, 0))
    from kata_xpu_device_plugin_tpu.ops.attention import reference_attention

    attn = reference_attention(q, ck, cv, causal=True, q_offset=cache_offset)
    x = x + attn.reshape(Bq, S, cfg.q_dim) @ layer["wo"].astype(x.dtype)
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gu = h @ layer["w_gateup"].astype(h.dtype)
    gate = jax.nn.gelu(gu[..., : cfg.d_ff], approximate=True)
    x = x + (gate * gu[..., cfg.d_ff :]) @ layer["w_down"].astype(x.dtype)
    return x, (ck, cv)


def make_decode_v0():
    def step(p):
        def s(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            logits, caches = forward(
                p, tok[:, None], cfg, positions=positions,
                kv_caches=caches, cache_offset=pos[0],
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        return s

    return steps_scan(step)


def make_decode_fused(unroll):
    def step(fp):
        def s(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            x = fp["embed"].astype(cfg.dtype)[tok[:, None]] * jnp.asarray(
                jnp.sqrt(cfg.d_model), cfg.dtype
            )

            def body(x, layer_and_cache):
                layer, cc = layer_and_cache
                return fused_layer(x, layer, positions, cc, pos[0])

            x, caches = lax.scan(body, x, (fp["layers"], caches), unroll=unroll)
            x = rms_norm(x, fp["final_norm"], cfg.norm_eps)
            logits = jnp.matmul(
                x, fp["embed"].T.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        return s

    return steps_scan(step)


def make_decode_ablate(skip_attn=False, skip_mlp=False, skip_unembed=False):
    def layer_fn(x, layer, positions, kv_cache, cache_offset):
        Bq, S, _ = x.shape
        ck, cv = kv_cache
        if not skip_attn:
            h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
            qkv = h @ layer["wqkv"].astype(h.dtype)
            q = qkv[..., : cfg.q_dim].reshape(Bq, S, cfg.n_heads, cfg.head_dim)
            k = qkv[..., cfg.q_dim : cfg.q_dim + cfg.kv_dim].reshape(
                Bq, S, cfg.n_kv_heads, cfg.head_dim
            )
            v = qkv[..., cfg.q_dim + cfg.kv_dim :].reshape(
                Bq, S, cfg.n_kv_heads, cfg.head_dim
            )
            q = rope(q, positions, cfg.rope_theta)
            k = rope(k, positions, cfg.rope_theta)
            ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, cache_offset, 0, 0))
            cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, cache_offset, 0, 0))
            from kata_xpu_device_plugin_tpu.ops.attention import reference_attention

            attn = reference_attention(q, ck, cv, causal=True, q_offset=cache_offset)
            x = x + attn.reshape(Bq, S, cfg.q_dim) @ layer["wo"].astype(x.dtype)
        if not skip_mlp:
            h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
            gu = h @ layer["w_gateup"].astype(h.dtype)
            gate = jax.nn.gelu(gu[..., : cfg.d_ff], approximate=True)
            x = x + (gate * gu[..., cfg.d_ff :]) @ layer["w_down"].astype(x.dtype)
        return x, (ck, cv)

    def step(fp):
        def s(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            x = fp["embed"].astype(cfg.dtype)[tok[:, None]] * jnp.asarray(
                jnp.sqrt(cfg.d_model), cfg.dtype
            )

            def body(x, layer_and_cache):
                layer, cc = layer_and_cache
                return layer_fn(x, layer, positions, cc, pos[0])

            x, caches = lax.scan(body, x, (fp["layers"], caches))
            x = rms_norm(x, fp["final_norm"], cfg.norm_eps)
            if skip_unembed:
                nxt = x[:, -1, 0].astype(jnp.int32) % cfg.vocab_size
            else:
                logits = jnp.matmul(
                    x, fp["embed"].T.astype(cfg.dtype),
                    preferred_element_type=jnp.float32,
                )
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        return s

    return steps_scan(step)


def suite_structural():
    print(f"params {cfg.num_params()/1e9:.3f}G -> ideal {ideal_ms:.3f} ms/step")
    pos = jnp.full((B,), PROMPT, jnp.int32)
    split = init_kv_caches(cfg, B, MAX_LEN)
    timeit("v0 current", make_decode_v0(), params, split, pos)
    timeit("v1 fused", make_decode_fused(1), fparams, split, pos)
    timeit("v2 fused+unroll3", make_decode_fused(3), fparams, split, pos)
    timeit("v3 fused+unroll6", make_decode_fused(6), fparams, split, pos)
    timeit("ab full", make_decode_ablate(), fparams, split, pos)
    timeit("ab no-attn", make_decode_ablate(skip_attn=True), fparams, split, pos)
    timeit("ab no-mlp", make_decode_ablate(skip_mlp=True), fparams, split, pos)
    timeit("ab no-unembed", make_decode_ablate(skip_unembed=True), fparams, split, pos)


# -------------------------------------------------------------- cache-layout

def make_decode_combined():
    KVD = cfg.kv_dim

    def step(fp):
        def s(carry, _):
            caches, tok, pos = carry
            positions = pos[:, None] * jnp.ones((B, 1), jnp.int32)
            x = fp["embed"].astype(cfg.dtype)[tok[:, None]] * jnp.asarray(
                jnp.sqrt(cfg.d_model), cfg.dtype
            )

            def body(x, layer_and_cache):
                layer, cache = layer_and_cache
                h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
                qkv = h @ layer["wqkv"].astype(h.dtype)
                q = qkv[..., : cfg.q_dim].reshape(B, 1, cfg.n_heads, cfg.head_dim)
                kv = qkv[..., cfg.q_dim :]  # [B, 1, 2*KVD]
                q = rope(q, positions, cfg.rope_theta)
                k = rope(
                    kv[..., :KVD].reshape(B, 1, cfg.n_kv_heads, cfg.head_dim),
                    positions, cfg.rope_theta,
                )
                kv = jnp.concatenate([k.reshape(B, 1, KVD), kv[..., KVD:]], -1)
                cache = lax.dynamic_update_slice(
                    cache, kv.astype(cache.dtype), (0, pos[0], 0)
                )
                ck = cache[..., :KVD].reshape(B, MAX_LEN, cfg.n_kv_heads, cfg.head_dim)
                cv = cache[..., KVD:].reshape(B, MAX_LEN, cfg.n_kv_heads, cfg.head_dim)
                G = cfg.n_heads // cfg.n_kv_heads
                qg = q.reshape(B, cfg.n_kv_heads, G, cfg.head_dim)
                logits = jnp.einsum(
                    "bhgd,bkhd->bhgk", qg, ck, preferred_element_type=jnp.float32
                ) * (1.0 / float(cfg.head_dim) ** 0.5)
                mask = jnp.arange(MAX_LEN)[None, :] <= pos[0]
                logits = jnp.where(mask[None, None], logits, -1e30)
                p = jax.nn.softmax(logits, axis=-1)
                attn = jnp.einsum(
                    "bhgk,bkhd->bhgd", p.astype(cv.dtype), cv,
                    preferred_element_type=jnp.float32,
                ).astype(x.dtype).reshape(B, 1, cfg.q_dim)
                x = x + attn @ layer["wo"].astype(x.dtype)
                h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
                gu = h @ layer["w_gateup"].astype(h.dtype)
                gate = jax.nn.gelu(gu[..., : cfg.d_ff], approximate=True)
                x = x + (gate * gu[..., cfg.d_ff :]) @ layer["w_down"].astype(x.dtype)
                return x, cache

            x, caches = lax.scan(body, x, (fp["layers"], caches))
            x = rms_norm(x, fp["final_norm"], cfg.norm_eps)
            logits = jnp.matmul(
                x, fp["embed"].T.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        return s

    return steps_scan(step)


def suite_cache_layout():
    print(f"params {cfg.num_params()/1e9:.3f}G -> ideal {ideal_ms:.3f} ms/step")
    pos = jnp.full((B,), PROMPT, jnp.int32)
    combined = jnp.zeros((cfg.n_layers, B, MAX_LEN, 2 * cfg.kv_dim), jnp.bfloat16)
    timeit("combined-cache", make_decode_combined(), fparams, combined, pos)


# --------------------------------------------------------------------- strip

def make_decode_strip(no_norms=False, no_rope=False, no_cachewrite=False,
                      no_softmax=False, matmuls_only=False):
    if matmuls_only:
        no_norms = no_rope = no_cachewrite = no_softmax = True

    def norm(x, scale):
        return x if no_norms else rms_norm(x, scale, cfg.norm_eps)

    def step(fp):
        def s(carry, _):
            caches, tok, pos = carry
            positions = jnp.full((B, 1), pos, jnp.int32)
            x = fp["embed"].astype(cfg.dtype)[tok[:, None]] * jnp.asarray(
                jnp.sqrt(cfg.d_model), cfg.dtype
            )

            def body(x, layer_and_cache):
                layer, (ck, cv) = layer_and_cache
                h = norm(x, layer["attn_norm"])
                qkv = h @ layer["wqkv"].astype(h.dtype)
                q = qkv[..., : cfg.q_dim].reshape(B, 1, cfg.n_heads, cfg.head_dim)
                k = qkv[..., cfg.q_dim : cfg.q_dim + cfg.kv_dim].reshape(
                    B, 1, cfg.n_kv_heads, cfg.head_dim
                )
                v = qkv[..., cfg.q_dim + cfg.kv_dim :].reshape(
                    B, 1, cfg.n_kv_heads, cfg.head_dim
                )
                if not no_rope:
                    q = rope(q, positions, cfg.rope_theta)
                    k = rope(k, positions, cfg.rope_theta)
                if not no_cachewrite:
                    ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, pos, 0, 0))
                    cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, pos, 0, 0))
                if matmuls_only:
                    attn = q.reshape(B, 1, cfg.q_dim)
                else:
                    G = cfg.n_heads // cfg.n_kv_heads
                    qg = q.reshape(B, cfg.n_kv_heads, G, cfg.head_dim)
                    logits = jnp.einsum(
                        "bhgd,bkhd->bhgk", qg, ck,
                        preferred_element_type=jnp.float32,
                    ) * (1.0 / float(cfg.head_dim) ** 0.5)
                    mask = jnp.arange(MAX_LEN)[None, :] <= pos
                    logits = jnp.where(mask[None, None], logits, -1e30)
                    p = logits if no_softmax else jax.nn.softmax(logits, axis=-1)
                    attn = jnp.einsum(
                        "bhgk,bkhd->bhgd", p.astype(cv.dtype), cv,
                        preferred_element_type=jnp.float32,
                    ).astype(x.dtype).reshape(B, 1, cfg.q_dim)
                x = x + attn @ layer["wo"].astype(x.dtype)
                h = norm(x, layer["mlp_norm"])
                gu = h @ layer["w_gateup"].astype(h.dtype)
                gate = jax.nn.gelu(gu[..., : cfg.d_ff], approximate=True)
                x = x + (gate * gu[..., cfg.d_ff :]) @ layer["w_down"].astype(x.dtype)
                return x, (ck, cv)

            x, caches = lax.scan(body, x, (fp["layers"], caches))
            x = norm(x, fp["final_norm"])
            logits = jnp.matmul(
                x, fp["embed"].T.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        return s

    return steps_scan(step)


def suite_strip():
    print(f"params {cfg.num_params()/1e9:.3f}G -> ideal {ideal_ms:.3f} ms/step")
    shape = (cfg.n_layers, B, MAX_LEN, cfg.n_kv_heads, cfg.head_dim)
    caches = (jnp.zeros(shape, jnp.bfloat16), jnp.zeros(shape, jnp.bfloat16))
    pos = jnp.int32(PROMPT)
    timeit("full", make_decode_strip(), fparams, caches, pos)
    timeit("no-norms", make_decode_strip(no_norms=True), fparams, caches, pos)
    timeit("no-rope", make_decode_strip(no_rope=True), fparams, caches, pos)
    timeit("no-cachewrite", make_decode_strip(no_cachewrite=True), fparams, caches, pos)
    timeit("no-softmax", make_decode_strip(no_softmax=True), fparams, caches, pos)
    timeit("matmuls-only", make_decode_strip(matmuls_only=True), fparams, caches, pos)


SUITES = {
    "structural": suite_structural,
    "cache-layout": suite_cache_layout,
    "strip": suite_strip,
}

if __name__ == "__main__":
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--suite", choices=sorted(SUITES), default="structural")
    suite = SUITES[ap.parse_args().suite]
    _init()
    suite()
