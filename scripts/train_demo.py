#!/usr/bin/env python3
"""End-to-end demo of the guest training/serving stack on a CPU mesh.

Runs the full user journey from docs/guest_guide.md at toy scale, with no
TPU and no downloads: synthesize a corpus → train with checkpointing →
simulate a preemption and resume → LoRA fine-tune → quantize → serve with
continuous batching + speculative decoding. Finishes in a few minutes on
one CPU core.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/train_demo.py
"""
from __future__ import annotations

import sys
import tempfile

sys.path.insert(0, "/root/repo")

import jax

jax.config.update("jax_platforms", "cpu")

from kata_xpu_device_plugin_tpu.compat.jaxapi import enable_compilation_cache

# Persistent XLA compile cache (ISSUE 3): the demo's second run skips the
# train/prefill/decode recompiles; KATA_TPU_COMPILE_CACHE=0 disables.
enable_compilation_cache()

import numpy as np
import jax.numpy as jnp

from kata_xpu_device_plugin_tpu.models import llama3_train_test
from kata_xpu_device_plugin_tpu.models.transformer import fuse_decoder_params
from kata_xpu_device_plugin_tpu.ops import (
    apply_lora,
    make_lora_train_step,
    merge_lora,
    quantize_decoder_params,
)
from kata_xpu_device_plugin_tpu.guest import serve_batch
from kata_xpu_device_plugin_tpu.parallel import (
    build_mesh,
    fit,
    make_loader,
    make_train_step,
)

cfg = llama3_train_test()
mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

# 1. corpus + pretrain with checkpointing, "preempted" after 4 steps
corpus = np.arange(8192, dtype=np.int32) % cfg.vocab_size
init_state, step = make_train_step(cfg, mesh)
ckpt_dir = tempfile.mkdtemp(prefix="demo_ckpt_")
key = jax.random.PRNGKey(0)


def loader():
    return make_loader(corpus, batch=8, seq_len=31, mesh=mesh, seed=1)


_, losses_a = fit(init_state, step, loader(), steps=4, key=key,
                  ckpt_dir=ckpt_dir, ckpt_every=2)
print(f"pretrain (interrupted at 4): losses {[round(l, 3) for l in losses_a]}")

# 2. resume from the checkpoint — replays the interrupted run exactly
state, losses_b = fit(init_state, step, loader(), steps=8, key=key,
                      ckpt_dir=ckpt_dir, ckpt_every=2)
print(f"resumed to 8:               losses {[round(l, 3) for l in losses_b]}")

# 3. LoRA fine-tune the pretrained params (base frozen)
params = state["params"]
adapted = apply_lora(params, jax.random.PRNGKey(1), rank=4)
lora_init, lora_step = make_lora_train_step(cfg, lr=1e-3)
lstate = lora_init(adapted)
ft_tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size)
for _ in range(5):
    lstate, lora_loss = lora_step(lstate, ft_tokens)
print(f"lora fine-tune: final loss {float(lora_loss):.3f}")

# 4. merge + fuse + int8-quantize for serving
served_params = quantize_decoder_params(
    fuse_decoder_params(merge_lora(lstate["params"]))
)

# 5. serve: continuous batching + speculative decoding + int8 KV arena
prompts = [corpus[i * 7 : i * 7 + 5 + i] for i in range(5)]
outs = serve_batch(served_params, cfg, prompts, max_new_tokens=16,
                   max_batch=2, max_len=64, speculative_k=3,
                   spec_opt_in=True, kv_quant=True)
print(f"served {len(outs)} requests through 2 slots; "
      f"first output: {outs[0].tolist()}")

# Telemetry (docs/observability.md): run with KATATPU_OBS=1 and the whole
# journey above — train steps, prefills, TTFTs, speculative rounds —
# lands in one JSONL event stream.
from kata_xpu_device_plugin_tpu import obs

sink = obs.default_sink()
if sink is not None:
    from kata_xpu_device_plugin_tpu.obs import read_events, summarize_phases

    evs = read_events(sink.path)
    print(f"obs: {sink.emitted} events -> {sink.path}")
    print(f"obs: train phases {summarize_phases(evs, prefix='train.')}")
    ttfts = [e["ttft_s"] for e in evs if e["name"] == "ttft"]
    print(f"obs: {len(ttfts)} TTFTs, max {max(ttfts):.3f}s" if ttfts else "")
print("demo complete")
