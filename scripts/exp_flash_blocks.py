#!/usr/bin/env python3
"""Flash prefill block-size sweep on the attached chip.

VERDICT r2 flagged the fixed 512×512 blocks as untuned; this sweeps
(block_q, block_k) over the bench's prefill shape (Gemma-2B, B=1, S=2048)
and prints ms per full-model prefill for each, plus the XLA reference.
"""
from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from kata_xpu_device_plugin_tpu.compat.jaxapi import enable_compilation_cache

# Persistent XLA compile cache (ISSUE 3): sweep reruns skip the
# multi-second recompiles; KATA_TPU_COMPILE_CACHE=0 disables.
enable_compilation_cache()

from kata_xpu_device_plugin_tpu.models import gemma_2b_bench
from kata_xpu_device_plugin_tpu.models.transformer import (
    forward,
    fuse_decoder_params,
    init_params,
)
from kata_xpu_device_plugin_tpu.ops.attention import reference_attention
from kata_xpu_device_plugin_tpu.ops.flash import pallas_flash_attention

cfg = gemma_2b_bench()
S = 2048

params = jax.jit(
    lambda k: fuse_decoder_params(init_params(k, cfg, dtype=jnp.bfloat16))
)(jax.random.PRNGKey(0))
jax.block_until_ready(params)


def time_prefill(attn_fn) -> float:  # jaxguard: hot
    fn = jax.jit(lambda p, t: forward(p, t, cfg, attn_fn=attn_fn)[:, -1])
    best = float("inf")
    for seed in range(5):
        toks = jax.random.randint(
            jax.random.PRNGKey(100 + seed), (1, S), 0, cfg.vocab_size,
            dtype=jnp.int32,
        )
        np.asarray(toks)  # jaxguard: allow(JG101) pre-materialize the input OUTSIDE the timed window
        t0 = time.perf_counter()
        np.asarray(fn(params, toks))  # jaxguard: allow(JG101, JG404) defensive: fn is an opaque jitted closure the dataflow cannot taint; the transfer IS the timing fence (JX004)
        elapsed = time.perf_counter() - t0
        if seed > 0:  # first run includes compile
            best = min(best, elapsed)
    return best


print(f"reference  {time_prefill(reference_attention)*1e3:8.2f} ms")
for bq in (256, 512, 1024):
    for bk in (256, 512, 1024):
        fn = partial(pallas_flash_attention, block_q=bq, block_k=bk)
        try:
            ms = time_prefill(fn) * 1e3
            print(f"flash {bq:4d}x{bk:<4d} {ms:8.2f} ms")
        except Exception as e:  # noqa: BLE001 — sweep survives bad configs
            print(f"flash {bq:4d}x{bk:<4d} failed: {type(e).__name__}")
