#!/usr/bin/env python3
"""Quantization quality harness (VERDICT r4 weak #5 / next #7).

``ops.quant``'s W8A8 docstring says "measure quality per model before
enabling in production" — this is the tool that performs that measurement.
It compares the quantization ladder against the full-precision baseline on
a fixed deterministic token set:

- **weight-only int8** (``quantize_decoder_params``)
- **W8A8** (``KATA_TPU_W8A8=1`` — int8×int8 dots with on-the-fly
  activation quantization)
- **int8 KV cache** (``kv_quantized=True`` decode)

Metrics per variant, all relative to the baseline forward on the SAME
tokens:

- ``ce`` / ``delta_ce`` — next-token cross-entropy and its drift. The
  token set is synthetic (no data ships in the image), so the absolute CE
  is meaningless; the DRIFT between variants is the quality signal.
- ``max_logit_drift`` / ``mean_logit_drift`` — max/mean |logit - logit_ref|
  over all positions: the primary closeness measure on synthetic tokens.
- ``top1_agree`` — fraction of positions whose argmax token matches the
  baseline (what greedy decode actually consumes).
- KV variant: greedy-token agreement over a decode run (``kv_agree``) and
  the step of first divergence, since the int8 cache only affects
  decode-from-cache reads.

CPU-runnable on the test configs (default); on the attached TPU the same
command evaluates the bench model: ``python scripts/eval_quality.py
--config gemma2_2b --dtype bfloat16``. ``make eval`` runs the CPU ladder.

One JSON line per variant on stdout; human summary on stderr.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="llama3_train_test",
                    help="models.<name>() config factory (e.g. "
                    "llama3_train_test, gemma2_test_config, gemma2_2b)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--decode-steps", type=int, default=64,
                    help="greedy steps for the int8-KV agreement metric")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (default when no TPU attached)")
    args = ap.parse_args()

    if args.cpu:
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from kata_xpu_device_plugin_tpu.compat.jaxapi import (
        enable_compilation_cache,
    )

    # Persistent XLA compile cache (ISSUE 3): ladder reruns (per-model
    # quality gates) skip recompiles; KATA_TPU_COMPILE_CACHE=0 disables.
    enable_compilation_cache()
    import jax.numpy as jnp
    import numpy as np

    from kata_xpu_device_plugin_tpu import models
    from kata_xpu_device_plugin_tpu.models.transformer import (
        forward, generate, init_params,
    )
    from kata_xpu_device_plugin_tpu.ops.quant import quantize_decoder_params

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    cfg = getattr(models, args.config)(dtype=dtype)
    params = init_params(jax.random.PRNGKey(args.seed), cfg, dtype=dtype)

    key = jax.random.PRNGKey(args.seed + 1)
    tokens = jax.random.randint(key, (args.batch, args.seq_len + 1), 0,
                                cfg.vocab_size)
    inputs, targets = tokens[:, :-1], np.asarray(tokens[:, 1:])

    def ce_and_logits(p):
        # A fresh jit per variant: W8A8 is read at trace time, so variants
        # must not share one cached executable.
        lg = jax.jit(lambda pp, tt: forward(pp, tt, cfg))(p, inputs)
        lg = np.asarray(lg, np.float32)
        lse = np.log(np.exp(lg - lg.max(-1, keepdims=True)).sum(-1)) + lg.max(-1)
        ce = float(np.mean(lse - np.take_along_axis(
            lg, targets[..., None], axis=-1)[..., 0]))
        return ce, lg

    def report(variant, ce, lg, base_ce, base_lg, extra=None):
        drift = np.abs(lg - base_lg)
        line = {
            "variant": variant,
            "config": args.config,
            "dtype": args.dtype,
            "ce": round(ce, 6),
            "delta_ce": round(ce - base_ce, 6),
            "max_logit_drift": round(float(drift.max()), 6),
            "mean_logit_drift": round(float(drift.mean()), 6),
            "top1_agree": round(
                float((lg.argmax(-1) == base_lg.argmax(-1)).mean()), 6),
            **(extra or {}),
        }
        print(json.dumps(line), flush=True)
        return line

    print(f"[eval_quality] {args.config} dtype={args.dtype} "
          f"B={args.batch} S={args.seq_len} on "
          f"{jax.devices()[0].platform}", file=sys.stderr)

    base_ce, base_lg = ce_and_logits(params)
    report("baseline", base_ce, base_lg, base_ce, base_lg)

    qparams = quantize_decoder_params(params)
    int8_ce, int8_lg = ce_and_logits(qparams)
    report("int8", int8_ce, int8_lg, base_ce, base_lg)

    from kata_xpu_device_plugin_tpu.ops.quant import set_w8a8

    set_w8a8(True)  # the env snapshot is import-time; toggle explicitly
    try:
        w8_ce, w8_lg = ce_and_logits(qparams)
        report("w8a8", w8_ce, w8_lg, base_ce, base_lg)
    finally:
        set_w8a8(False)

    # int8 KV cache: only decode-from-cache reads differ, so measure where
    # it bites — greedy token agreement over a decode run.
    prompt = tokens[:, : min(32, args.seq_len)]
    max_len = prompt.shape[1] + args.decode_steps
    ref_toks = np.asarray(generate(params, prompt, cfg, args.decode_steps,
                                   max_len=max_len))
    kv_toks = np.asarray(generate(params, prompt, cfg, args.decode_steps,
                                  max_len=max_len, kv_quantized=True))
    agree = ref_toks == kv_toks
    # Per row, the first divergent step (or decode_steps if none).
    first_div = [
        int(np.argmin(a)) if not a.all() else args.decode_steps for a in agree
    ]
    print(json.dumps({
        "variant": "int8_kv",
        "config": args.config,
        "dtype": args.dtype,
        "kv_agree": round(float(agree.mean()), 6),
        "first_divergence_step": min(first_div),
        "decode_steps": args.decode_steps,
    }), flush=True)

    print("[eval_quality] done — delta_ce/top1_agree are the go/no-go "
          "numbers for enabling int8/W8A8 on this model", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
